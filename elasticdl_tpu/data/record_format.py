"""TRec: the framework's record file format (RecordIO-equivalent).

The reference stores training data in RecordIO files and shards work by
(filename, start_record, end_record) ranges scanned with
``recordio.Scanner(shard, start, count)`` (reference:
data/reader/recordio_reader.py:27-62). The `recordio` package is a CPython/Go
artifact; this framework defines its own simple, seekable format so the same
dynamic-sharding semantics work anywhere:

    file  := MAGIC(8) VERSION(u32) record* footer
    record:= len(u64) crc32(u32) payload[len]
    footer:= offsets[count](u64 each) count(u64) FOOT_MAGIC(8)

The trailing offset index gives O(1) seek-to-record-i, which is what makes
record-range tasks cheap (the reference gets this from recordio's chunk
index). A C++ scanner with the same layout lives in
``elasticdl_tpu/native/recordio.cc``; this module is the pure-Python
reference implementation and fallback.
"""

import os
import struct
import zlib

MAGIC = b"TRECIO\x00\x01"
FOOT_MAGIC = b"TRECEND\x00"
VERSION = 1

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_REC_HDR = struct.Struct("<QI")  # payload_len, crc32


class RecordWriter(object):
    """Append-only writer. Use as a context manager; the index footer is
    written on close."""

    def __init__(self, path):
        self._f = open(path, "wb")
        self._offsets = []
        self._f.write(MAGIC)
        self._f.write(_U32.pack(VERSION))
        self._closed = False

    def write(self, payload):
        if isinstance(payload, str):
            payload = payload.encode("utf-8")
        self._offsets.append(self._f.tell())
        self._f.write(_REC_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)

    def close(self):
        if self._closed:
            return
        for off in self._offsets:
            self._f.write(_U64.pack(off))
        self._f.write(_U64.pack(len(self._offsets)))
        self._f.write(FOOT_MAGIC)
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def get_record_count(path):
    size = os.path.getsize(path)
    tail = _U64.size + len(FOOT_MAGIC)
    if size < len(MAGIC) + _U32.size + tail:
        raise ValueError("%s is not a TRec file (too small)" % path)
    with open(path, "rb") as f:
        f.seek(size - tail)
        count = _U64.unpack(f.read(_U64.size))[0]
        if f.read(len(FOOT_MAGIC)) != FOOT_MAGIC:
            raise ValueError("%s has a corrupt TRec footer" % path)
    return count


def _read_index(path):
    size = os.path.getsize(path)
    count = get_record_count(path)
    tail = _U64.size + len(FOOT_MAGIC)
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError("%s is not a TRec file" % path)
        f.seek(size - tail - _U64.size * count)
        data = f.read(_U64.size * count)
    return [_U64.unpack_from(data, i * _U64.size)[0] for i in range(count)]


class Scanner(object):
    """Iterate `count` records of `path` starting at record `start`
    (signature parity with recordio.Scanner as used by the reference's
    RecordIODataReader)."""

    def __init__(self, path, start=0, count=-1):
        self._offsets = _read_index(path)
        n = len(self._offsets)
        if count < 0:
            count = n - start
        self._path = path
        self._start = max(0, start)
        self._end = min(n, start + count)

    def __iter__(self):
        with open(self._path, "rb") as f:
            for i in range(self._start, self._end):
                f.seek(self._offsets[i])
                hdr = f.read(_REC_HDR.size)
                length, crc = _REC_HDR.unpack(hdr)
                payload = f.read(length)
                if zlib.crc32(payload) != crc:
                    raise IOError(
                        "CRC mismatch in %s at record %d" % (self._path, i)
                    )
                yield payload


def write_records(path, payloads):
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
