"""A minimal host-side dataset pipeline (tf.data replacement).

The reference feeds workers with ``tf.data.Dataset.from_generator`` over the
task stream and lets the model zoo's ``dataset_fn`` map/shuffle/batch it
(worker/task_data_service.py:163-203, model zoo dataset_fn convention). TPU
input pipelines are host-side numpy anyway (device work happens inside jit),
so this module provides the small composable subset the model zoo needs:

    Dataset.from_generator(gen_fn)
      .map(fn) .shuffle(buffer_size) .batch(n, drop_remainder) .prefetch(n)

Batching stacks dict-of-ndarray (or tuple) elements into leading-batch-dim
numpy arrays, ready for ``jax.device_put`` with a batch sharding.
"""

import collections
import queue
import random
import threading

import numpy as np


class Dataset(object):
    def __init__(self, source_fn):
        # source_fn: () -> iterator of elements
        self._source_fn = source_fn

    @staticmethod
    def from_generator(gen_fn):
        return Dataset(gen_fn)

    @staticmethod
    def from_list(items):
        return Dataset(lambda: iter(list(items)))

    def map(self, fn):
        src = self._source_fn

        def gen():
            for x in src():
                yield fn(x)

        return Dataset(gen)

    def filter(self, pred):
        src = self._source_fn

        def gen():
            for x in src():
                if pred(x):
                    yield x

        return Dataset(gen)

    def shuffle(self, buffer_size, seed=None):
        src = self._source_fn

        def gen():
            rng = random.Random(seed)
            buf = []
            for x in src():
                buf.append(x)
                if len(buf) >= buffer_size:
                    i = rng.randrange(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            for x in buf:
                yield x

        return Dataset(gen)

    def repeat(self, count=None):
        src = self._source_fn

        def gen():
            n = 0
            while count is None or n < count:
                emitted = False
                for x in src():
                    emitted = True
                    yield x
                n += 1
                if not emitted:
                    return

        return Dataset(gen)

    def take(self, count):
        src = self._source_fn

        def gen():
            it = src()
            for _ in range(count):
                try:
                    yield next(it)
                except StopIteration:
                    return

        return Dataset(gen)

    def batch(self, batch_size, drop_remainder=False):
        src = self._source_fn

        def gen():
            buf = []
            for x in src():
                buf.append(x)
                if len(buf) == batch_size:
                    yield _stack(buf)
                    buf = []
            if buf and not drop_remainder:
                yield _stack(buf)

        return Dataset(gen)

    def prefetch(self, buffer_size=1):
        src = self._source_fn

        def gen():
            q = queue.Queue(maxsize=max(1, buffer_size))
            _SENTINEL = object()
            stop = threading.Event()
            err = []

            def producer():
                try:
                    for x in src():
                        # bounded put that notices consumer abandonment, so
                        # a dropped iterator can't leak a blocked thread and
                        # its open file handles
                        while not stop.is_set():
                            try:
                                q.put(x, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
                except BaseException as e:  # propagate into consumer
                    err.append(e)
                finally:
                    while not stop.is_set():
                        try:
                            q.put(_SENTINEL, timeout=0.1)
                            break
                        except queue.Full:
                            continue

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            try:
                while True:
                    x = q.get()
                    if x is _SENTINEL:
                        if err:
                            raise err[0]
                        return
                    yield x
            finally:
                stop.set()

        return Dataset(gen)

    def __iter__(self):
        return self._source_fn()


def _stack(elements):
    """Stack a list of homogeneous elements (dicts, tuples, or arrays) into a
    batched element with a leading batch axis."""
    first = elements[0]
    if isinstance(first, dict):
        return collections.OrderedDict(
            (k, _stack([e[k] for e in elements])) for k in first
        )
    if isinstance(first, tuple):
        return tuple(
            _stack([e[i] for e in elements]) for i in range(len(first))
        )
    arrs = [np.asarray(e) for e in elements]
    return np.stack(arrs, axis=0)


def pad_batch(batch, batch_size):
    """Pad the leading axis of every array in `batch` to `batch_size` by
    repeating the last element; returns (padded_batch, true_count).

    XLA-compiled steps need static shapes; the final partial batch of a task
    is padded up and the loss/metric masked by true_count.
    """
    def leading(x):
        return np.asarray(x).shape[0]

    def pad(x):
        x = np.asarray(x)
        n = x.shape[0]
        if n == batch_size:
            return x
        reps = np.repeat(x[-1:], batch_size - n, axis=0)
        return np.concatenate([x, reps], axis=0)

    if isinstance(batch, dict):
        n = leading(next(iter(batch.values())))
        return {k: pad(v) for k, v in batch.items()}, n
    if isinstance(batch, tuple):
        n = leading(batch[0] if not isinstance(batch[0], dict) else next(iter(batch[0].values())))
        return tuple(
            {k: pad(v) for k, v in b.items()} if isinstance(b, dict) else pad(b)
            for b in batch
        ), n
    n = leading(batch)
    return pad(batch), n
