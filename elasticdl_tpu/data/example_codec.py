"""Example codec: {feature_name: ndarray} <-> record payload bytes.

The reference serializes training examples as TF `tf.train.Example` protos
inside RecordIO (e.g. model_zoo/mnist_functional_api/mnist_functional_api.py
`prepare_data_for_a_single_file`). This framework is TF-free: an example is a
dict of named ndarrays serialized with the same binary tensor layout as the
control plane (common/tensor_utils.py).
"""

from elasticdl_tpu.common.tensor_utils import (
    deserialize_ndarray_dict,
    serialize_ndarray_dict,
)


def encode_example(features):
    """features: {name: ndarray-like} -> bytes."""
    return serialize_ndarray_dict(features)


def decode_example(payload):
    """bytes -> {name: ndarray}."""
    return deserialize_ndarray_dict(payload)
