"""Sequence packing for LM training: fill fixed-length rows with
multiple variable-length token sequences instead of padding each to the
model length.

TPU-first rationale: XLA wants static [batch, seq_len] shapes, so short
documents either waste FLOPs as padding or waste data as truncation.
Packing keeps the MXU busy on real tokens; correctness comes from the
model side (model_zoo/transformer_lm accepts ``segment_ids``: attention
is confined to each packed run by the flash kernels' segment masks and
positions restart per run — ops/attention.py), and from the label side
here (cross-segment next-token targets are masked with ``IGNORE_LABEL``
so a document never predicts the first token of the next one).

The reference has no packing story (its feature columns pad —
/root/reference/elasticdl_preprocessing/layers/to_sparse.py handles
ragged inputs by sparsifying instead); this is net-new surface.
"""

import numpy as np

# target value the LM loss ignores (model_zoo/transformer_lm.loss
# averages over labels >= 0 only)
IGNORE_LABEL = -100


def pack_sequences(sequences, row_len, pad_id=0):
    """Greedy first-fit-decreasing packing.

    sequences: iterable of 1-D int arrays/lists (token ids, each len
    >= 2 — a sequence contributes len-1 next-token targets).
    row_len: packed row length (the model seq_len).

    Returns (tokens, segment_ids, labels), each [n_rows, row_len] int32:
      * tokens      — packed ids, pad_id in the tail slack
      * segment_ids — 0..k per row, one id per packed sequence; the pad
                      tail gets its own fresh id (it attends only to
                      itself and its labels are ignored)
      * labels      — tokens shifted left WITHIN each segment; the last
                      position of every segment and all pad positions
                      are IGNORE_LABEL.

    Sequences longer than row_len are split into row_len-sized chunks
    (the standard LM blocking); a trailing chunk of length < 2 is
    dropped (it would carry no target).
    """
    chunks = []
    for seq in sequences:
        seq = np.asarray(seq, np.int32).reshape(-1)
        for start in range(0, len(seq), row_len):
            chunk = seq[start:start + row_len]
            if len(chunk) >= 2:
                chunks.append(chunk)
    if not chunks:
        raise ValueError("no packable sequences (all shorter than 2)")
    # first-fit-decreasing: longest chunks first, into the first row
    # with enough slack
    chunks.sort(key=len, reverse=True)
    rows = []  # list of lists of chunks
    slack = []
    for chunk in chunks:
        for i, s in enumerate(slack):
            if len(chunk) <= s:
                rows[i].append(chunk)
                slack[i] -= len(chunk)
                break
        else:
            rows.append([chunk])
            slack.append(row_len - len(chunk))

    n = len(rows)
    tokens = np.full((n, row_len), pad_id, np.int32)
    segment_ids = np.zeros((n, row_len), np.int32)
    labels = np.full((n, row_len), IGNORE_LABEL, np.int32)
    for r, row_chunks in enumerate(rows):
        tokens[r], segment_ids[r], labels[r] = _layout_row(
            row_chunks, row_len, pad_id
        )
    return tokens, segment_ids, labels


def _layout_row(row_chunks, row_len, pad_id):
    """One packed row from its list of chunks: (tokens, segment_ids,
    labels), each 1-D [row_len] int32. Next-token targets stay within
    each segment (the last position of a segment has no in-segment
    successor); the pad tail gets its own fresh segment id and ignored
    labels."""
    tokens = np.full(row_len, pad_id, np.int32)
    segment_ids = np.zeros(row_len, np.int32)
    labels = np.full(row_len, IGNORE_LABEL, np.int32)
    at = 0
    for sid, chunk in enumerate(row_chunks):
        m = len(chunk)
        tokens[at:at + m] = chunk
        segment_ids[at:at + m] = sid
        labels[at:at + m - 1] = chunk[1:]
        at += m
    if at < row_len:
        segment_ids[at:] = len(row_chunks)
    return tokens, segment_ids, labels


def pack_dataset(dataset, row_len, pad_id=0, open_rows=8):
    """Streaming packer over a host Dataset pipeline.

    dataset: a `data.dataset.Dataset` (or any iterable) of 1-D int
    token sequences of VARIABLE length (e.g. the per-record output of
    a tokenizing `map`). Returns a new Dataset of packed LM examples
    `({"tokens": [row_len], "segment_ids": [row_len]}, labels)` —
    `.batch(n)` stacks them into model-ready packed batches, so a zoo
    ``dataset_fn`` can pack inside the worker's task stream instead of
    offline.

    First-fit over up to `open_rows` partially-filled rows: a row is
    emitted as soon as its slack cannot hold another target (< 2
    tokens), when room must be made, or at stream end — bounded memory,
    single pass, deterministic for a given input order."""
    from elasticdl_tpu.data.dataset import Dataset

    def gen():
        rows = []   # open rows: lists of chunks
        slack = []  # remaining capacity per open row

        def emit(i):
            tokens, segment_ids, labels = _layout_row(
                rows.pop(i), row_len, pad_id
            )
            slack.pop(i)
            return (
                {"tokens": tokens, "segment_ids": segment_ids},
                labels,
            )

        for seq in dataset:
            seq = np.asarray(seq, np.int32).reshape(-1)
            for start in range(0, len(seq), row_len):
                chunk = seq[start:start + row_len]
                if len(chunk) < 2:
                    continue
                for i, s in enumerate(slack):
                    if len(chunk) <= s:
                        rows[i].append(chunk)
                        slack[i] -= len(chunk)
                        if slack[i] < 2:
                            yield emit(i)
                        break
                else:
                    if len(rows) >= open_rows:
                        # make room: emit the fullest open row
                        yield emit(int(np.argmin(slack)))
                    rows.append([chunk])
                    slack.append(row_len - len(chunk))
                    if slack[-1] < 2:
                        yield emit(len(rows) - 1)
        while rows:
            yield emit(0)

    return Dataset(gen)


def packing_efficiency(sequences, row_len):
    """Real-token fraction of the packed layout — the measure of what
    packing buys on a given corpus (1.0 = rows fully filled with real
    tokens). A segment of m tokens carries m-1 targets, so real tokens
    per segment = its non-ignored labels + 1; pad segments carry no
    targets and count 0."""
    tokens, segment_ids, labels = pack_sequences(sequences, row_len)
    real = 0
    for r in range(tokens.shape[0]):
        for sid in np.unique(segment_ids[r]):
            targets = int(
                (labels[r][segment_ids[r] == sid] != IGNORE_LABEL).sum()
            )
            if targets:
                real += targets + 1
    return real / tokens.size
