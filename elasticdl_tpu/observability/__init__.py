"""Distributed tracing + latency histograms, dependency-free.

The observability substrate for the whole system: request-scoped span
trees that survive process hops (client -> router -> replica -> decode
step, master -> worker -> report), a bounded per-process span recorder
exporting Chrome-trace/Perfetto JSON, and fixed-bucket log-linear
histograms (HDR-style: O(1) record, mergeable across processes) that
back every latency percentile the status RPCs and the serving bench
report — one definition of p99, everywhere.

Modules:

* tracing    — trace/span ids, `Span`, the ring-buffer `SpanRecorder`,
               the process-global recorder, Chrome-trace conversion
* histogram  — `LogLinearHistogram` + the shared `percentiles()` entry
* metrics    — the LIVE metrics plane: `TimeSeriesRing` (windowed
               counter/gauge/bucket deltas, mergeable by addition) +
               Prometheus text exposition (`render_prometheus`,
               `MetricsServer` behind --metrics_port/EDL_METRICS_PORT)
* slo        — declared objectives evaluated as multi-window burn
               rates over the ring (`SloSpec`, `BurnRateEngine`)
* promparse  — INDEPENDENT text-format parser (shares nothing with the
               renderer) for drills/tests to round-trip expositions,
               OpenMetrics exemplars included
* dump       — CLI merging per-process span exports into one trace
               (``python -m elasticdl_tpu.observability.dump``), with
               per-service drop accounting in the artifact
* forensics  — per-request cause attribution: `attribute()` folds a
               span tree into an ordered latency breakdown + a
               dominant cause from the closed `CAUSES` taxonomy
* collector  — the fleet collector
               (``python -m elasticdl_tpu.observability.collector``):
               scrape /metrics fleet-wide, re-evaluate declared SLOs,
               join burning buckets to exemplar traces, attribute
               them, and emit the incident report
* runtime_health — the runtime's SELF-report: `tracked_jit` +
               `RecompileSentry` (compilations per named executable,
               steady-boundary anomalies), `DeviceMemoryAccountant`
               (byte-ledger vs live-buffer reconciliation, leak
               watermark), `ProgressWatchdog` + `FlightRecorder`
               (stall detection off the scheduler thread, atomic
               diagnostic bundles), `install_sigusr2_dump`

Design doc: docs/designs/observability.md.
"""

from elasticdl_tpu.observability.forensics import (  # noqa: F401
    CAUSES,
    attribute,
    cause_histogram,
)
from elasticdl_tpu.observability.histogram import (  # noqa: F401
    LogLinearHistogram,
    percentiles,
)
from elasticdl_tpu.observability.metrics import (  # noqa: F401
    MetricsServer,
    TimeSeriesRing,
    merge_window_deltas,
    metrics_port_default,
    render_prometheus,
)
from elasticdl_tpu.observability.slo import (  # noqa: F401
    BurnRateEngine,
    SloSpec,
    default_router_slos,
)
from elasticdl_tpu.observability.tracing import (  # noqa: F401
    Span,
    SpanRecorder,
    chrome_trace,
    configure,
    new_span_id,
    new_trace_id,
    recorder,
)
