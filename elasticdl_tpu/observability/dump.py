"""Merge per-process span exports into one Chrome-trace JSON.

Every traced process (router, replicas, master, workers) writes its
ring buffer to ``$EDL_TRACE_DIR/spans-<service>-<pid>.json`` on clean
shutdown (tracing.SpanRecorder.flush). This tool stitches those files
into a single timeline — spans keep their trace/span/parent ids, so
one request dispatched through the router shows up as ONE tree with
the router's dispatch spans parenting each replica's serve span.

    python -m elasticdl_tpu.observability.dump \\
        --dir /tmp/edl-traces --out trace.json

Open ``trace.json`` at ui.perfetto.dev (or chrome://tracing). The
chaos drill calls `merge_dir` directly and asserts the causal
structure of what it finds (scripts/run_router_chaos_drill.py).
"""

import argparse
import glob
import json
import os
import sys

from elasticdl_tpu.observability.tracing import (
    TRACE_DIR_ENV,
    chrome_trace,
    group_by_trace,
)


def merge_dir(trace_dir):
    """(span dicts, per-process meta) from every spans-*.json export
    under `trace_dir`. Unreadable files are reported in meta, not
    fatal: a SIGKILLed process's missing/partial export must never
    block merging the survivors."""
    spans, meta = [], []
    for path in sorted(glob.glob(
            os.path.join(trace_dir, "spans-*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            meta.append({"path": path, "error": str(e)})
            continue
        meta.append({
            "path": path,
            "service": doc.get("service", "?"),
            "pid": doc.get("pid", 0),
            "spans": len(doc.get("spans", ())),
            "dropped": doc.get("dropped", 0),
        })
        spans.extend(doc.get("spans", ()))
    return spans, meta


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--dir", default=os.environ.get(TRACE_DIR_ENV, ""),
        help="directory of spans-*.json exports (default: "
             "$EDL_TRACE_DIR)",
    )
    parser.add_argument("--out", default="trace.json",
                        help="merged Chrome-trace JSON output path")
    args = parser.parse_args(argv)
    if not args.dir:
        print("dump: no --dir and no $%s set" % TRACE_DIR_ENV,
              file=sys.stderr)
        return 2
    spans, meta = merge_dir(args.dir)
    with open(args.out, "w") as f:
        json.dump(chrome_trace(spans), f)
    dropped = sum(m.get("dropped", 0) for m in meta)
    errors = [m for m in meta if "error" in m]
    print(
        "dump: merged %d spans across %d traces from %d exports -> %s"
        " (%d dropped ring entries%s)"
        % (len(spans), len(group_by_trace(spans)),
           len(meta) - len(errors), args.out, dropped,
           "; %d unreadable exports" % len(errors) if errors else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
