"""Merge per-process span exports into one Chrome-trace JSON.

Every traced process (router, replicas, master, workers) writes its
ring buffer to ``$EDL_TRACE_DIR/spans-<service>-<pid>.json`` on clean
shutdown (tracing.SpanRecorder.flush). This tool stitches those files
into a single timeline — spans keep their trace/span/parent ids, so
one request dispatched through the router shows up as ONE tree with
the router's dispatch spans parenting each replica's serve span.

    python -m elasticdl_tpu.observability.dump \\
        --dir /tmp/edl-traces --out trace.json

Open ``trace.json`` at ui.perfetto.dev (or chrome://tracing). The
chaos drill calls `merge_dir` directly and asserts the causal
structure of what it finds (scripts/run_router_chaos_drill.py).
"""

import argparse
import glob
import json
import os
import sys

from elasticdl_tpu.observability.tracing import (
    TRACE_DIR_ENV,
    chrome_trace,
    group_by_trace,
)


def merge_dir(trace_dir):
    """(span dicts, per-process meta) from every spans-*.json export
    under `trace_dir`. Unreadable files are reported in meta, not
    fatal: a SIGKILLed process's missing/partial export must never
    block merging the survivors."""
    spans, meta = [], []
    for path in sorted(glob.glob(
            os.path.join(trace_dir, "spans-*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            meta.append({"path": path, "error": str(e)})
            continue
        meta.append({
            "path": path,
            "service": doc.get("service", "?"),
            "pid": doc.get("pid", 0),
            "spans": len(doc.get("spans", ())),
            "dropped": doc.get("dropped", 0),
            # the two-tier recorder's other loss accounting: retained-
            # tier evictions and healthy roots sampled out (tail-based
            # retention, tracing.py) — zero on pre-tier exports
            "retained": doc.get("retained", 0),
            "retained_dropped": doc.get("retained_dropped", 0),
            "sampled_out": doc.get("sampled_out", 0),
        })
        spans.extend(doc.get("spans", ()))
    return spans, meta


def drops_by_service(meta):
    """{service: spans irrecoverably dropped} across the merged
    exports (ring drop-oldest + retained-tier evictions; sampled-out
    healthy roots are NOT drops — they were declined, not lost). A
    forensics verdict over a service with nonzero drops is evidence-
    incomplete and must say so rather than pose as the whole story."""
    out = {}
    for m in meta:
        if "error" in m:
            continue
        d = int(m.get("dropped", 0)) + int(m.get("retained_dropped", 0))
        if d:
            out[m["service"]] = out.get(m["service"], 0) + d
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--dir", default=os.environ.get(TRACE_DIR_ENV, ""),
        help="directory of spans-*.json exports (default: "
             "$EDL_TRACE_DIR)",
    )
    parser.add_argument("--out", default="trace.json",
                        help="merged Chrome-trace JSON output path")
    args = parser.parse_args(argv)
    if not args.dir:
        print("dump: no --dir and no $%s set" % TRACE_DIR_ENV,
              file=sys.stderr)
        return 2
    spans, meta = merge_dir(args.dir)
    drops = drops_by_service(meta)
    doc = chrome_trace(spans)
    # Chrome-trace "otherData" rides unknown keys through Perfetto
    # untouched: the merged evidence accounting lives IN the artifact,
    # so a trace file can say its own evidence is incomplete
    doc["otherData"] = {
        "exports": meta,
        "drops_by_service": drops,
        "evidence_complete": not drops
        and not any("error" in m for m in meta),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f)
    errors = [m for m in meta if "error" in m]
    print(
        "dump: merged %d spans across %d traces from %d exports -> %s"
        " (%d unreadable exports)"
        % (len(spans), len(group_by_trace(spans)),
           len(meta) - len(errors), args.out, len(errors))
    )
    if drops:
        print(
            "dump: EVIDENCE INCOMPLETE — spans dropped before export: "
            + ", ".join("%s=%d" % (svc, n)
                        for svc, n in sorted(drops.items()))
        )
    else:
        print("dump: evidence complete (zero recorder drops)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
