"""Fixed-bucket log-linear latency histograms (HDR-style).

One bucket scheme for the WHOLE system, frozen at import time, so any
two histograms — a replica's TTFT recorder, the router's merge of
three replicas, the serving bench's client-side samples — are
mergeable by elementwise bucket-count addition and comparable without
unit negotiation. Replacing point-gauges/EWMAs with these is what lets
`ServerStatus`/`router_status` answer "what is p99 right now" and lets
`bench_serving.py` and the live telemetry compute percentiles from the
SAME code path (definitionally identical numbers).

Scheme (values are non-negative floats; the system records
milliseconds): the value is scaled by ``1/RESOLUTION`` to an integer
``n``; the first ``SUBBUCKETS`` buckets are linear (width =
RESOLUTION), above that each power-of-two "decade" is split into
``SUBBUCKETS/2`` linear subbuckets — so relative error is bounded by
``2/SUBBUCKETS`` (~3.1% at 64) at EVERY magnitude, from a 10 us queue
pop to an hours-long stall, with ``NUM_BUCKETS`` (= 832) total
buckets. Record cost is O(1): one divide + ``int.bit_length`` + two
shifts — cheap enough for the decode loop.

Thread-safety: none here, by design — every histogram in the system
lives behind its owner's telemetry lock (serving/telemetry.py), and
the bench records from a single aggregation thread. Keeping the lock
out of the hot `record` keeps the overhead bound honest.

EXEMPLARS: a histogram can answer "p99 is 1.2 s" but not "WHICH
request" — the gap between a burning SLO gauge and a trace an operator
can open. `record(value, trace_id=...)` optionally attaches a
per-bucket exemplar (trace_id, value, unix_ts), bounded to
``EXEMPLAR_SLOTS`` buckets with the HIGHEST-value buckets winning (the
tail is what forensics wants; nobody debugs the p10 bucket) and the
max-value sample winning within a bucket — which also makes the merge
associative, so exemplars survive bucket-addition aggregation the same
way counts do. The wire form (`exemplars_wire`/`from_counts`) rides
next to `to_counts()` and the Prometheus renderer emits OpenMetrics
exemplar syntax on `_bucket` lines; observability/promparse.py
validates it independently.
"""

import math
import time

#: smallest distinguishable value (0.01 => 10 us when recording ms)
RESOLUTION = 0.01
#: linear subbuckets per power-of-two decade (power of two)
SUBBUCKETS = 64
_SUB_BITS = SUBBUCKETS.bit_length() - 1  # log2(SUBBUCKETS)
_HALF = SUBBUCKETS // 2
#: decades above the linear range (covers ~2.8 hours in ms)
_DECADES = 24
NUM_BUCKETS = SUBBUCKETS + _DECADES * _HALF
#: max buckets carrying an exemplar per histogram; the HIGHEST-value
#: buckets win a slot (tail forensics), the max-value sample wins
#: within a bucket (merge stays associative)
EXEMPLAR_SLOTS = 16


def bucket_index(value):
    """O(1) bucket index for a non-negative value."""
    try:
        n = int(value / RESOLUTION)
    except (OverflowError, ValueError):  # inf: clamp to the top
        return NUM_BUCKETS - 1
    if n < SUBBUCKETS:
        return n if n >= 0 else 0
    e = n.bit_length() - _SUB_BITS  # >= 1
    if e > _DECADES:  # beyond the top decade: clamp
        return NUM_BUCKETS - 1
    m = n >> e  # in [SUBBUCKETS/2, SUBBUCKETS)
    return SUBBUCKETS + (e - 1) * _HALF + (m - _HALF)


def bucket_bounds(idx):
    """(lower, upper) value bounds of bucket `idx` (upper exclusive)."""
    if idx < SUBBUCKETS:
        return idx * RESOLUTION, (idx + 1) * RESOLUTION
    k = idx - SUBBUCKETS
    e = k // _HALF + 1
    m = _HALF + k % _HALF
    return (m << e) * RESOLUTION, ((m + 1) << e) * RESOLUTION


class LogLinearHistogram(object):
    """Mergeable fixed-bucket histogram with exact count/sum/min/max.

    ``counts`` is a dense list of ``NUM_BUCKETS`` ints; `to_counts()`
    trims trailing zeros for wire transport (the `repeated int64`
    histogram fields on the status protos) and `from_counts()`
    rebuilds — merge is elementwise addition, so per-replica
    histograms aggregate at the router without losing percentile
    fidelity (percentiles of merged counts, never averages of
    percentiles)."""

    __slots__ = ("counts", "count", "sum", "min", "max", "exemplars")

    def __init__(self):
        self.counts = [0] * NUM_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0
        #: bucket index -> (trace_id, value, unix_ts); bounded to
        #: EXEMPLAR_SLOTS entries, highest-index buckets win a slot
        self.exemplars = {}

    def record(self, value, trace_id=None, ts=None):
        value = float(value)
        if not 0.0 <= value < math.inf:  # negative/NaN/inf: refuse
            return
        idx = bucket_index(value)
        self.counts[idx] += 1
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if trace_id:
            self._note_exemplar(
                idx, str(trace_id), value,
                time.time() if ts is None else float(ts),
            )

    def _note_exemplar(self, idx, trace_id, value, ts):
        """Keep at most EXEMPLAR_SLOTS exemplar-carrying buckets, the
        HIGHEST-value buckets winning a slot and the max-value sample
        winning within a bucket — the ordering that makes merge
        associative and keeps the p99 tail covered."""
        cur = self.exemplars.get(idx)
        if cur is not None:
            if value >= cur[1]:
                self.exemplars[idx] = (trace_id, value, ts)
            return
        if len(self.exemplars) >= EXEMPLAR_SLOTS:
            low = min(self.exemplars)
            if idx <= low:
                return  # a lower bucket never evicts a higher one
            del self.exemplars[low]
        self.exemplars[idx] = (trace_id, value, ts)

    def merge(self, other):
        """Fold `other` in (elementwise bucket addition); exemplars
        merge keep-max-per-bucket under the same slot bound."""
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.count:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        for idx, (tid, value, ts) in other.exemplars.items():
            self._note_exemplar(idx, tid, value, ts)
        return self

    def percentile(self, q):
        """Value at percentile `q` (0..100): the midpoint of the
        bucket where the cumulative count crosses rank ceil(q% * n),
        clamped into the exact [min, max] envelope (so a one-sample
        histogram answers that sample's bucket, not a bucket edge).
        0.0 when empty — proto-friendly: absent percentile == 0."""
        if not self.count:
            return 0.0
        rank = max(1, int(math.ceil(q / 100.0 * self.count)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                lo, hi = bucket_bounds(i)
                mid = (lo + hi) / 2.0
                return min(max(mid, self.min), self.max)
        return self.max  # unreachable unless counts were tampered

    def snapshot(self, qs=(50, 90, 99)):
        """{"p50": ..., "p90": ..., "p99": ..., "count": n} — the
        status-RPC shape."""
        out = {"p%d" % q: self.percentile(q) for q in qs}
        out["count"] = self.count
        return out

    def to_counts(self):
        """Dense counts with trailing zeros trimmed (wire form)."""
        last = 0
        for i, c in enumerate(self.counts):
            if c:
                last = i + 1
        return self.counts[:last]

    def exemplars_wire(self):
        """Exemplar wire form riding next to to_counts():
        {bucket_index: [trace_id, value, unix_ts]} — JSON-safe (lists,
        not tuples; from_counts re-accepts string keys a JSON
        round-trip produces)."""
        return {
            idx: [tid, value, ts]
            for idx, (tid, value, ts) in self.exemplars.items()
        }

    @classmethod
    def from_counts(cls, counts, exemplars=None):
        """Rebuild from wire-form counts (+ optional exemplar map).
        min/max/sum degrade to bucket-midpoint estimates (bounded by
        the scheme's relative error) — good enough for percentile
        math, which only needs the counts."""
        h = cls()
        for i, c in enumerate(counts):
            c = int(c)
            if c <= 0 or i >= NUM_BUCKETS:
                continue
            h.counts[i] = c
            h.count += c
            lo, hi = bucket_bounds(i)
            mid = (lo + hi) / 2.0
            h.sum += mid * c
            h.min = min(h.min, mid)
            h.max = max(h.max, mid)
        for idx, ex in (exemplars or {}).items():
            tid, value, ts = ex
            h._note_exemplar(int(idx), str(tid), float(value),
                             float(ts))
        return h


def percentiles(values, qs=(50, 90, 99)):
    """Percentiles of `values` through the shared histogram — THE
    entry point bench_serving.py and the tests use, so offline bench
    numbers and live status-RPC numbers come from one definition.
    {"p50": ...} with None entries when `values` is empty (a bench
    with no completions has no percentile, unlike a live histogram
    where 0 means "no data yet")."""
    if not values:
        return {"p%d" % q: None for q in qs}
    h = LogLinearHistogram()
    for v in values:
        h.record(v)
    return {"p%d" % q: round(h.percentile(q), 3) for q in qs}
