"""Fixed-bucket log-linear latency histograms (HDR-style).

One bucket scheme for the WHOLE system, frozen at import time, so any
two histograms — a replica's TTFT recorder, the router's merge of
three replicas, the serving bench's client-side samples — are
mergeable by elementwise bucket-count addition and comparable without
unit negotiation. Replacing point-gauges/EWMAs with these is what lets
`ServerStatus`/`router_status` answer "what is p99 right now" and lets
`bench_serving.py` and the live telemetry compute percentiles from the
SAME code path (definitionally identical numbers).

Scheme (values are non-negative floats; the system records
milliseconds): the value is scaled by ``1/RESOLUTION`` to an integer
``n``; the first ``SUBBUCKETS`` buckets are linear (width =
RESOLUTION), above that each power-of-two "decade" is split into
``SUBBUCKETS/2`` linear subbuckets — so relative error is bounded by
``2/SUBBUCKETS`` (~3.1% at 64) at EVERY magnitude, from a 10 us queue
pop to an hours-long stall, with ``NUM_BUCKETS`` (= 832) total
buckets. Record cost is O(1): one divide + ``int.bit_length`` + two
shifts — cheap enough for the decode loop.

Thread-safety: none here, by design — every histogram in the system
lives behind its owner's telemetry lock (serving/telemetry.py), and
the bench records from a single aggregation thread. Keeping the lock
out of the hot `record` keeps the overhead bound honest.
"""

import math

#: smallest distinguishable value (0.01 => 10 us when recording ms)
RESOLUTION = 0.01
#: linear subbuckets per power-of-two decade (power of two)
SUBBUCKETS = 64
_SUB_BITS = SUBBUCKETS.bit_length() - 1  # log2(SUBBUCKETS)
_HALF = SUBBUCKETS // 2
#: decades above the linear range (covers ~2.8 hours in ms)
_DECADES = 24
NUM_BUCKETS = SUBBUCKETS + _DECADES * _HALF


def bucket_index(value):
    """O(1) bucket index for a non-negative value."""
    try:
        n = int(value / RESOLUTION)
    except (OverflowError, ValueError):  # inf: clamp to the top
        return NUM_BUCKETS - 1
    if n < SUBBUCKETS:
        return n if n >= 0 else 0
    e = n.bit_length() - _SUB_BITS  # >= 1
    if e > _DECADES:  # beyond the top decade: clamp
        return NUM_BUCKETS - 1
    m = n >> e  # in [SUBBUCKETS/2, SUBBUCKETS)
    return SUBBUCKETS + (e - 1) * _HALF + (m - _HALF)


def bucket_bounds(idx):
    """(lower, upper) value bounds of bucket `idx` (upper exclusive)."""
    if idx < SUBBUCKETS:
        return idx * RESOLUTION, (idx + 1) * RESOLUTION
    k = idx - SUBBUCKETS
    e = k // _HALF + 1
    m = _HALF + k % _HALF
    return (m << e) * RESOLUTION, ((m + 1) << e) * RESOLUTION


class LogLinearHistogram(object):
    """Mergeable fixed-bucket histogram with exact count/sum/min/max.

    ``counts`` is a dense list of ``NUM_BUCKETS`` ints; `to_counts()`
    trims trailing zeros for wire transport (the `repeated int64`
    histogram fields on the status protos) and `from_counts()`
    rebuilds — merge is elementwise addition, so per-replica
    histograms aggregate at the router without losing percentile
    fidelity (percentiles of merged counts, never averages of
    percentiles)."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * NUM_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def record(self, value):
        value = float(value)
        if not 0.0 <= value < math.inf:  # negative/NaN/inf: refuse
            return
        self.counts[bucket_index(value)] += 1
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def merge(self, other):
        """Fold `other` in (elementwise bucket addition)."""
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.count:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        return self

    def percentile(self, q):
        """Value at percentile `q` (0..100): the midpoint of the
        bucket where the cumulative count crosses rank ceil(q% * n),
        clamped into the exact [min, max] envelope (so a one-sample
        histogram answers that sample's bucket, not a bucket edge).
        0.0 when empty — proto-friendly: absent percentile == 0."""
        if not self.count:
            return 0.0
        rank = max(1, int(math.ceil(q / 100.0 * self.count)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                lo, hi = bucket_bounds(i)
                mid = (lo + hi) / 2.0
                return min(max(mid, self.min), self.max)
        return self.max  # unreachable unless counts were tampered

    def snapshot(self, qs=(50, 90, 99)):
        """{"p50": ..., "p90": ..., "p99": ..., "count": n} — the
        status-RPC shape."""
        out = {"p%d" % q: self.percentile(q) for q in qs}
        out["count"] = self.count
        return out

    def to_counts(self):
        """Dense counts with trailing zeros trimmed (wire form)."""
        last = 0
        for i, c in enumerate(self.counts):
            if c:
                last = i + 1
        return self.counts[:last]

    @classmethod
    def from_counts(cls, counts):
        """Rebuild from wire-form counts. min/max/sum degrade to
        bucket-midpoint estimates (bounded by the scheme's relative
        error) — good enough for percentile math, which only needs
        the counts."""
        h = cls()
        for i, c in enumerate(counts):
            c = int(c)
            if c <= 0 or i >= NUM_BUCKETS:
                continue
            h.counts[i] = c
            h.count += c
            lo, hi = bucket_bounds(i)
            mid = (lo + hi) / 2.0
            h.sum += mid * c
            h.min = min(h.min, mid)
            h.max = max(h.max, mid)
        return h


def percentiles(values, qs=(50, 90, 99)):
    """Percentiles of `values` through the shared histogram — THE
    entry point bench_serving.py and the tests use, so offline bench
    numbers and live status-RPC numbers come from one definition.
    {"p50": ...} with None entries when `values` is empty (a bench
    with no completions has no percentile, unlike a live histogram
    where 0 means "no data yet")."""
    if not values:
        return {"p%d" % q: None for q in qs}
    h = LogLinearHistogram()
    for v in values:
        h.record(v)
    return {"p%d" % q: round(h.percentile(q), 3) for q in qs}
