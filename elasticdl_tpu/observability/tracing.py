"""Request-scoped distributed tracing: spans, the ring-buffer
recorder, and Chrome-trace/Perfetto export.

The context model is deliberately tiny — three ids, all hex strings:

* ``trace_id``        one per REQUEST (or per training task), minted
                      at admission wherever the request first enters
                      the system (router, direct client, or the
                      master handing out a task);
* ``span_id``         one per span;
* ``parent_span_id``  the causal edge. Crossing a process boundary
                      means copying ``(trace_id, span_id)`` into the
                      RPC's trace fields; the receiver starts its span
                      with ``parent_span_id = <sender's span_id>``.

That is enough to reassemble ONE tree per request across any number
of processes and retries: a hedge or a re-dispatch creates SIBLING
spans under the same parent, a mid-stream replica loss shows as a
failed child next to the replacement — causality survives exactly the
hops the router/master elasticity story creates.

Standard span events (attach with ``span.event(name, **attrs)``):
``queued``, ``seated``, ``prefill``, ``first_token``, ``completed``,
``expired``, ``rejected``, ``redispatched``, ``hedged``,
``hedge_win``, ``breaker_trip``, ``shed``, ``fault_injected``,
``fetched``, ``reported``. Nothing enforces the vocabulary — but the
chaos drill's structural assertions and the dump tool's summary key
on these names, so stick to them.

Recording is ALWAYS on and bounded: finished spans land in a
lock-guarded ring buffer (drop-OLDEST on overflow, with a ``dropped``
counter — a traced process can never grow without bound, and the drop
is visible).

TAIL-BASED RETENTION: the ring alone has a forensic blind spot — under
pressure, drop-oldest evicts exactly the traces that explain a latency
spike, because slow requests are by definition OLD by the time anyone
looks. The recorder therefore runs TWO tiers: classifier hooks
(``add_classifier``) judge each finished span — ``True`` moves the
span AND every recorded span of its trace into a separately-bounded
RETAINED tier (and pins later-finishing spans of that trace there
too), ``False`` marks a healthy root that is kept only with
probability ``sample_rate`` (below it, the root and its trace's spans
leave the ring — counted in ``sampled_out``), ``None`` means "not
mine" and falls through to the next hook / the plain ring. The
router installs a hook judging its request roots against the declared
SLO thresholds (RouterConfig.slo_*), the replica one judging `serve`
spans against each request's OWN deadline — retention policy reuses
the thresholds the system already declares, no new config surface.
With no hooks installed, behavior is exactly the PR 6 single ring.

Export to disk happens only when ``EDL_TRACE_DIR`` is set: each process writes ``spans-<service>-<pid>.json`` there
(explicitly via ``flush()`` on clean shutdown, plus an atexit
backstop), and ``python -m elasticdl_tpu.observability.dump`` merges
every per-process export into one Chrome-trace JSON that loads in
Perfetto (ui.perfetto.dev) or chrome://tracing.

Timestamps are ``time.time()`` (wall clock): spans from different
processes must land on one timeline, which monotonic clocks cannot
give across processes. Good enough for the single-host drills this
serves; cross-host skew shifts whole processes, never re-orders one
process's spans.
"""

import atexit
import json
import os
import random
import threading
import time
from collections import deque

TRACE_DIR_ENV = "EDL_TRACE_DIR"

_DEFAULT_CAPACITY = 4096
#: the retained tier's own bound (slow/failed traces); deliberately
#: smaller than the ring — retention is for the tail, not a second
#: copy of everything
_DEFAULT_RETAINED_CAPACITY = 2048


def new_trace_id():
    return os.urandom(8).hex()


def new_span_id():
    return os.urandom(8).hex()


class Span(object):
    """One timed operation. Created by ``SpanRecorder.start_span``;
    call ``finish()`` (or use as a context manager) to seal it into
    the recorder's ring. Unfinished spans are never exported.

    Cross-thread use is the NORM here (a serving request's span is
    touched by the gRPC handler thread and the scheduler thread):
    ``event``/``set`` are plain appends/updates — atomic under the
    GIL — and ``finish`` is idempotent under the recorder's lock, so
    a terminal race records the span exactly once."""

    __slots__ = ("name", "trace_id", "span_id", "parent_span_id",
                 "service", "start", "end", "status", "attrs",
                 "events", "_recorder")

    def __init__(self, recorder, name, trace_id, parent_span_id,
                 attrs, start):
        self._recorder = recorder
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_span_id = parent_span_id or ""
        self.service = recorder.service
        self.start = start
        self.end = None
        self.status = None
        self.attrs = dict(attrs)
        self.events = []

    def event(self, name, **attrs):
        """Timestamped point annotation inside the span."""
        self.events.append((self._recorder.clock(), name, attrs))
        return self

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def finish(self, status="ok"):
        """Seal the span into the recorder's ring (idempotent: the
        first finish wins; later calls are no-ops)."""
        self._recorder._finish(self, status)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, _tb):
        self.finish("ok" if exc_type is None else "error")
        return False

    def to_dict(self):
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "service": self.service,
            "start": self.start,
            "end": self.end,
            "status": self.status,
            "attrs": self.attrs,
            "events": [
                {"ts": ts, "name": name, "attrs": attrs}
                for ts, name, attrs in list(self.events)
            ],
        }


class SpanRecorder(object):
    """Per-process bounded store of FINISHED spans.

    Memory is bounded by construction: `capacity` spans, drop-oldest
    with a monotone ``dropped`` counter (never drop-newest — the most
    recent spans are the ones a post-incident export wants). All
    mutation under one lock; `start_span` allocates outside it (span
    construction is lock-free), so tracing adds one short critical
    section per REQUEST, not per token."""

    def __init__(self, service="proc", capacity=_DEFAULT_CAPACITY,
                 clock=time.time,
                 retained_capacity=_DEFAULT_RETAINED_CAPACITY,
                 sample_rate=1.0, seed=None):
        self.service = service
        self.capacity = int(capacity)
        self.clock = clock
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans = deque()
        # tail-based retention: verdict hooks + the retained tier
        self.retained_capacity = int(retained_capacity)
        self.retained_dropped = 0
        self.sampled_out = 0
        self.sample_rate = float(sample_rate)
        self._retained = deque()
        self._retained_traces = set()
        self._classifiers = []
        self._rand = random.Random(seed)

    def add_classifier(self, fn):
        """Register a verdict hook `fn(span) -> True | False | None`:
        True = retain the span's whole trace in the retained tier,
        False = healthy root (probabilistic sample), None = not this
        hook's span (fall through). Hooks run under the recorder lock
        at finish time — keep them pure and cheap. Idempotent per
        function object."""
        with self._lock:
            if fn not in self._classifiers:
                self._classifiers.append(fn)
        return fn

    def remove_classifier(self, fn):
        """Unregister a hook (no-op if absent) — lifecycle owners
        (e.g. a stopping Router) drop their hook so a long-lived test
        process never accumulates stale verdicts."""
        with self._lock:
            self._classifiers = [
                f for f in self._classifiers if f != fn
            ]

    def clear_classifiers(self):
        with self._lock:
            self._classifiers = []

    def start_span(self, name, trace_id=None, parent_span_id="",
                   **attrs):
        """New span; mints a fresh trace when `trace_id` is falsy
        (this IS admission: the point a request first gets traced)."""
        return Span(self, name, trace_id or new_trace_id(),
                    parent_span_id, attrs, self.clock())

    def _verdict_locked(self, span):
        """First non-None hook verdict, or None. A hook that raises is
        treated as abstaining — observability must never take the
        serving path down with it."""
        for fn in self._classifiers:
            try:
                verdict = fn(span)
            except Exception:  # noqa: BLE001 - hooks must not crash us
                verdict = None
            if verdict is not None:
                return bool(verdict)
        return None

    def _retain_locked(self, span):
        """Move `span` — and every already-recorded span of its trace —
        into the retained tier, pinning the trace so stragglers follow.
        The tier is bounded drop-oldest with its own counter."""
        self._retained_traces.add(span.trace_id)
        moved = [s for s in self._spans
                 if s.trace_id == span.trace_id]
        if moved:
            self._spans = deque(
                s for s in self._spans
                if s.trace_id != span.trace_id
            )
        for s in moved:
            self._retained.append(s)
        self._retained.append(span)
        while len(self._retained) > self.retained_capacity:
            victim = self._retained.popleft()
            self.retained_dropped += 1
            if not any(s.trace_id == victim.trace_id
                       for s in self._retained):
                self._retained_traces.discard(victim.trace_id)

    def _finish(self, span, status):
        with self._lock:
            if span.end is not None:  # idempotent terminal
                return
            span.end = self.clock()
            span.status = status
            if span.trace_id in self._retained_traces:
                self._retain_locked(span)
                return
            verdict = self._verdict_locked(span)
            if verdict is True:
                self._retain_locked(span)
                return
            if verdict is False and self._rand.random() >= self.sample_rate:
                # healthy root sampled OUT: its trace's spans leave the
                # ring too — pressure relief is the whole point
                before = len(self._spans)
                self._spans = deque(
                    s for s in self._spans
                    if s.trace_id != span.trace_id
                )
                self.sampled_out += 1 + (before - len(self._spans))
                return
            self._spans.append(span)
            while len(self._spans) > self.capacity:
                self._spans.popleft()
                self.dropped += 1

    def __len__(self):
        with self._lock:
            return len(self._retained) + len(self._spans)

    def snapshot(self):
        """Every recorded span, retained tier first (it holds the
        oldest surviving evidence)."""
        with self._lock:
            return list(self._retained) + list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._retained.clear()
            self._retained_traces.clear()
            self.dropped = 0
            self.retained_dropped = 0
            self.sampled_out = 0

    def export(self):
        """The on-disk per-process document the dump tool merges."""
        with self._lock:
            spans = list(self._retained) + list(self._spans)
            retained = len(self._retained)
            dropped = self.dropped
            retained_dropped = self.retained_dropped
            sampled_out = self.sampled_out
        return {
            "service": self.service,
            "pid": os.getpid(),
            "dropped": dropped,
            "retained": retained,
            "retained_dropped": retained_dropped,
            "sampled_out": sampled_out,
            "spans": [s.to_dict() for s in spans],
        }

    def write(self, path):
        """Atomic JSON write (tmp + rename): a process dying mid-write
        can never leave a torn file for the merger to choke on."""
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(self.export(), f)
        os.replace(tmp, path)
        return path

    def flush(self, trace_dir=None):
        """Write this process's spans into the trace directory
        (EDL_TRACE_DIR unless given). No-op returning None when no
        directory is configured — the zero-config production default
        keeps spans in memory only."""
        trace_dir = trace_dir or os.environ.get(TRACE_DIR_ENV, "")
        if not trace_dir:
            return None
        os.makedirs(trace_dir, exist_ok=True)
        safe = "".join(
            c if c.isalnum() or c in "-_." else "-"
            for c in self.service
        )
        return self.write(os.path.join(
            trace_dir, "spans-%s-%d.json" % (safe, os.getpid())
        ))


# ------------------------------------------------- process-global recorder

_RECORDER = SpanRecorder()
_ATEXIT_ARMED = False


def recorder():
    """The process-global recorder every subsystem records into (one
    file per process at export time). Tests may swap service/capacity
    via configure() or construct private SpanRecorders."""
    return _RECORDER


def configure(service=None, capacity=None):
    """Name this process's recorder (e.g. ``replica:50051``,
    ``router``, ``master``) and arm the atexit flush backstop. Called
    by the process entrypoints; safe to call repeatedly."""
    global _ATEXIT_ARMED
    if service:
        _RECORDER.service = service
    if capacity:
        _RECORDER.capacity = int(capacity)
    if not _ATEXIT_ARMED:
        _ATEXIT_ARMED = True
        atexit.register(lambda: _RECORDER.flush())
    return _RECORDER


# ------------------------------------------------------ chrome conversion


def group_by_trace(span_dicts):
    """{trace_id: [span dicts]} — the structural-assertion entry the
    tests and the chaos drill use."""
    by_trace = {}
    for s in span_dicts:
        by_trace.setdefault(s["trace_id"], []).append(s)
    return by_trace


def trace_roots(span_dicts):
    """Spans with no parent IN the set (cross-process parents that
    were never exported — e.g. a SIGKILLed process — leave their
    children as roots rather than hiding them)."""
    ids = {s["span_id"] for s in span_dicts}
    return [s for s in span_dicts
            if not s["parent_span_id"] or s["parent_span_id"] not in ids]


def children_of(span_dicts, parent_span_id):
    return [s for s in span_dicts
            if s["parent_span_id"] == parent_span_id]


def chrome_trace(span_dicts):
    """Convert merged span dicts into Chrome-trace JSON (the "JSON
    Array Format" both chrome://tracing and Perfetto ingest).

    Layout: one Chrome "process" per service (process_name metadata),
    one "thread" per trace within it — so opening the file shows each
    request's spans stacked on one row, per tier. Every slice carries
    trace_id/span_id/parent_span_id (plus the span attrs and status)
    in ``args``; span events become instant events on the same row."""
    services = sorted({s["service"] for s in span_dicts})
    pid_of = {svc: i + 1 for i, svc in enumerate(services)}
    tid_of = {}
    events = []
    for svc, pid in pid_of.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": svc},
        })
    for s in sorted(span_dicts, key=lambda d: (d["start"], d["name"])):
        pid = pid_of[s["service"]]
        tid = tid_of.setdefault((pid, s["trace_id"]),
                                len(tid_of) + 1)
        end = s["end"] if s["end"] is not None else s["start"]
        args = dict(s["attrs"])
        args.update({
            "trace_id": s["trace_id"],
            "span_id": s["span_id"],
            "parent_span_id": s["parent_span_id"],
            "status": s["status"],
        })
        events.append({
            "name": s["name"], "cat": s["service"], "ph": "X",
            "pid": pid, "tid": tid,
            "ts": s["start"] * 1e6,
            "dur": max(0.0, (end - s["start"])) * 1e6,
            "args": args,
        })
        for ev in s["events"]:
            events.append({
                "name": ev["name"], "cat": s["service"], "ph": "i",
                "s": "t", "pid": pid, "tid": tid,
                "ts": ev["ts"] * 1e6,
                "args": dict(ev["attrs"],
                             trace_id=s["trace_id"],
                             span_id=s["span_id"]),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
