"""Request-scoped distributed tracing: spans, the ring-buffer
recorder, and Chrome-trace/Perfetto export.

The context model is deliberately tiny — three ids, all hex strings:

* ``trace_id``        one per REQUEST (or per training task), minted
                      at admission wherever the request first enters
                      the system (router, direct client, or the
                      master handing out a task);
* ``span_id``         one per span;
* ``parent_span_id``  the causal edge. Crossing a process boundary
                      means copying ``(trace_id, span_id)`` into the
                      RPC's trace fields; the receiver starts its span
                      with ``parent_span_id = <sender's span_id>``.

That is enough to reassemble ONE tree per request across any number
of processes and retries: a hedge or a re-dispatch creates SIBLING
spans under the same parent, a mid-stream replica loss shows as a
failed child next to the replacement — causality survives exactly the
hops the router/master elasticity story creates.

Standard span events (attach with ``span.event(name, **attrs)``):
``queued``, ``seated``, ``prefill``, ``first_token``, ``completed``,
``expired``, ``rejected``, ``redispatched``, ``hedged``,
``hedge_win``, ``breaker_trip``, ``shed``, ``fault_injected``,
``fetched``, ``reported``. Nothing enforces the vocabulary — but the
chaos drill's structural assertions and the dump tool's summary key
on these names, so stick to them.

Recording is ALWAYS on and bounded: finished spans land in a
lock-guarded ring buffer (drop-OLDEST on overflow, with a ``dropped``
counter — a traced process can never grow without bound, and the drop
is visible). Export to disk happens only when ``EDL_TRACE_DIR`` is
set: each process writes ``spans-<service>-<pid>.json`` there
(explicitly via ``flush()`` on clean shutdown, plus an atexit
backstop), and ``python -m elasticdl_tpu.observability.dump`` merges
every per-process export into one Chrome-trace JSON that loads in
Perfetto (ui.perfetto.dev) or chrome://tracing.

Timestamps are ``time.time()`` (wall clock): spans from different
processes must land on one timeline, which monotonic clocks cannot
give across processes. Good enough for the single-host drills this
serves; cross-host skew shifts whole processes, never re-orders one
process's spans.
"""

import atexit
import json
import os
import threading
import time
from collections import deque

TRACE_DIR_ENV = "EDL_TRACE_DIR"

_DEFAULT_CAPACITY = 4096


def new_trace_id():
    return os.urandom(8).hex()


def new_span_id():
    return os.urandom(8).hex()


class Span(object):
    """One timed operation. Created by ``SpanRecorder.start_span``;
    call ``finish()`` (or use as a context manager) to seal it into
    the recorder's ring. Unfinished spans are never exported.

    Cross-thread use is the NORM here (a serving request's span is
    touched by the gRPC handler thread and the scheduler thread):
    ``event``/``set`` are plain appends/updates — atomic under the
    GIL — and ``finish`` is idempotent under the recorder's lock, so
    a terminal race records the span exactly once."""

    __slots__ = ("name", "trace_id", "span_id", "parent_span_id",
                 "service", "start", "end", "status", "attrs",
                 "events", "_recorder")

    def __init__(self, recorder, name, trace_id, parent_span_id,
                 attrs, start):
        self._recorder = recorder
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_span_id = parent_span_id or ""
        self.service = recorder.service
        self.start = start
        self.end = None
        self.status = None
        self.attrs = dict(attrs)
        self.events = []

    def event(self, name, **attrs):
        """Timestamped point annotation inside the span."""
        self.events.append((self._recorder.clock(), name, attrs))
        return self

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def finish(self, status="ok"):
        """Seal the span into the recorder's ring (idempotent: the
        first finish wins; later calls are no-ops)."""
        self._recorder._finish(self, status)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, _tb):
        self.finish("ok" if exc_type is None else "error")
        return False

    def to_dict(self):
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "service": self.service,
            "start": self.start,
            "end": self.end,
            "status": self.status,
            "attrs": self.attrs,
            "events": [
                {"ts": ts, "name": name, "attrs": attrs}
                for ts, name, attrs in list(self.events)
            ],
        }


class SpanRecorder(object):
    """Per-process bounded store of FINISHED spans.

    Memory is bounded by construction: `capacity` spans, drop-oldest
    with a monotone ``dropped`` counter (never drop-newest — the most
    recent spans are the ones a post-incident export wants). All
    mutation under one lock; `start_span` allocates outside it (span
    construction is lock-free), so tracing adds one short critical
    section per REQUEST, not per token."""

    def __init__(self, service="proc", capacity=_DEFAULT_CAPACITY,
                 clock=time.time):
        self.service = service
        self.capacity = int(capacity)
        self.clock = clock
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans = deque()

    def start_span(self, name, trace_id=None, parent_span_id="",
                   **attrs):
        """New span; mints a fresh trace when `trace_id` is falsy
        (this IS admission: the point a request first gets traced)."""
        return Span(self, name, trace_id or new_trace_id(),
                    parent_span_id, attrs, self.clock())

    def _finish(self, span, status):
        with self._lock:
            if span.end is not None:  # idempotent terminal
                return
            span.end = self.clock()
            span.status = status
            self._spans.append(span)
            while len(self._spans) > self.capacity:
                self._spans.popleft()
                self.dropped += 1

    def __len__(self):
        with self._lock:
            return len(self._spans)

    def snapshot(self):
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def export(self):
        """The on-disk per-process document the dump tool merges."""
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
        return {
            "service": self.service,
            "pid": os.getpid(),
            "dropped": dropped,
            "spans": [s.to_dict() for s in spans],
        }

    def write(self, path):
        """Atomic JSON write (tmp + rename): a process dying mid-write
        can never leave a torn file for the merger to choke on."""
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(self.export(), f)
        os.replace(tmp, path)
        return path

    def flush(self, trace_dir=None):
        """Write this process's spans into the trace directory
        (EDL_TRACE_DIR unless given). No-op returning None when no
        directory is configured — the zero-config production default
        keeps spans in memory only."""
        trace_dir = trace_dir or os.environ.get(TRACE_DIR_ENV, "")
        if not trace_dir:
            return None
        os.makedirs(trace_dir, exist_ok=True)
        safe = "".join(
            c if c.isalnum() or c in "-_." else "-"
            for c in self.service
        )
        return self.write(os.path.join(
            trace_dir, "spans-%s-%d.json" % (safe, os.getpid())
        ))


# ------------------------------------------------- process-global recorder

_RECORDER = SpanRecorder()
_ATEXIT_ARMED = False


def recorder():
    """The process-global recorder every subsystem records into (one
    file per process at export time). Tests may swap service/capacity
    via configure() or construct private SpanRecorders."""
    return _RECORDER


def configure(service=None, capacity=None):
    """Name this process's recorder (e.g. ``replica:50051``,
    ``router``, ``master``) and arm the atexit flush backstop. Called
    by the process entrypoints; safe to call repeatedly."""
    global _ATEXIT_ARMED
    if service:
        _RECORDER.service = service
    if capacity:
        _RECORDER.capacity = int(capacity)
    if not _ATEXIT_ARMED:
        _ATEXIT_ARMED = True
        atexit.register(lambda: _RECORDER.flush())
    return _RECORDER


# ------------------------------------------------------ chrome conversion


def group_by_trace(span_dicts):
    """{trace_id: [span dicts]} — the structural-assertion entry the
    tests and the chaos drill use."""
    by_trace = {}
    for s in span_dicts:
        by_trace.setdefault(s["trace_id"], []).append(s)
    return by_trace


def trace_roots(span_dicts):
    """Spans with no parent IN the set (cross-process parents that
    were never exported — e.g. a SIGKILLed process — leave their
    children as roots rather than hiding them)."""
    ids = {s["span_id"] for s in span_dicts}
    return [s for s in span_dicts
            if not s["parent_span_id"] or s["parent_span_id"] not in ids]


def children_of(span_dicts, parent_span_id):
    return [s for s in span_dicts
            if s["parent_span_id"] == parent_span_id]


def chrome_trace(span_dicts):
    """Convert merged span dicts into Chrome-trace JSON (the "JSON
    Array Format" both chrome://tracing and Perfetto ingest).

    Layout: one Chrome "process" per service (process_name metadata),
    one "thread" per trace within it — so opening the file shows each
    request's spans stacked on one row, per tier. Every slice carries
    trace_id/span_id/parent_span_id (plus the span attrs and status)
    in ``args``; span events become instant events on the same row."""
    services = sorted({s["service"] for s in span_dicts})
    pid_of = {svc: i + 1 for i, svc in enumerate(services)}
    tid_of = {}
    events = []
    for svc, pid in pid_of.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": svc},
        })
    for s in sorted(span_dicts, key=lambda d: (d["start"], d["name"])):
        pid = pid_of[s["service"]]
        tid = tid_of.setdefault((pid, s["trace_id"]),
                                len(tid_of) + 1)
        end = s["end"] if s["end"] is not None else s["start"]
        args = dict(s["attrs"])
        args.update({
            "trace_id": s["trace_id"],
            "span_id": s["span_id"],
            "parent_span_id": s["parent_span_id"],
            "status": s["status"],
        })
        events.append({
            "name": s["name"], "cat": s["service"], "ph": "X",
            "pid": pid, "tid": tid,
            "ts": s["start"] * 1e6,
            "dur": max(0.0, (end - s["start"])) * 1e6,
            "args": args,
        })
        for ev in s["events"]:
            events.append({
                "name": ev["name"], "cat": s["service"], "ph": "i",
                "s": "t", "pid": pid, "tid": tid,
                "ts": ev["ts"] * 1e6,
                "args": dict(ev["attrs"],
                             trace_id=s["trace_id"],
                             span_id=s["span_id"]),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
