"""Live metrics plane: windowed time-series ring + Prometheus-text
exposition, dependency-free.

PR 6 gave the system mergeable lifetime histograms and closed counter
sets; what was still missing was a LIVE signal plane — percentiles were
lifetime aggregates polled through status RPCs, there was no standard
scrape surface, and nothing windowed existed for an SLO burn rate to be
computed over. This module supplies both halves:

* **TimeSeriesRing** — a bounded ring of fixed-interval windows. Each
  window holds counter DELTAS, last-observed gauges, and histogram
  BUCKET deltas (the PR 6 log-linear scheme's `to_counts()` wire form),
  so any trailing horizon can answer "what happened in the last N
  seconds" — the exact shape an SLO burn rate (observability/slo.py)
  and a windowed prefix-hit-rate need. Windows from different replicas
  merge the same way `router_status` merges lifetime histograms:
  counter deltas add, bucket deltas add elementwise
  (`merge_window_deltas`) — never averages. The ring is bounded by
  construction: overflow drops the OLDEST window and bumps a monotone
  `dropped` counter (the span recorder's contract, kept).

* **Prometheus text exposition** — `render_prometheus` renders families
  of counters/gauges/histograms in text format 0.0.4 (`# HELP`/`# TYPE`
  lines, `_bucket{le=...}`/`_sum`/`_count` series for histograms, with
  cumulative buckets at the shared log-linear scheme's bounds), and
  `MetricsServer` serves the rendered page from a stdlib `http.server`
  thread at `GET /metrics` — off by default, armed per process by
  `--metrics_port` / `EDL_METRICS_PORT`. No client library, no
  dependency: any Prometheus-compatible scraper (or `curl`) reads it.

Naming rules (the whole system follows them; the independent parser in
observability/promparse.py and the drill assertions key on the shapes):

    edl_<service>_<counter>_total        counter (monotone)
    edl_<service>_<gauge>                gauge   (last value)
    edl_<service>_<hist>  + _bucket/_sum/_count   histogram (ms)

Thread-safety: the ring is NOT internally locked (same contract as
LogLinearHistogram) — every ring in the system lives behind its owning
telemetry's lock. MetricsServer's collect callback runs on the HTTP
thread; collectors must do their own locking (the telemetry
`prometheus()` methods snapshot under their locks).
"""

import math
import os
import threading
import time
from collections import deque

from elasticdl_tpu.observability.histogram import (
    NUM_BUCKETS,
    bucket_bounds,
)


def metrics_port_default():
    """EDL_METRICS_PORT resolves the scrape port when the config/CLI
    leaves it unset: unset/empty = exposition OFF (None), an integer =
    bind that port (0 = ephemeral, for drills and tests)."""
    text = os.environ.get("EDL_METRICS_PORT", "")
    if text == "":
        return None
    try:
        return int(text)
    except ValueError:
        return None


# ------------------------------------------------------------------ ring


def add_counts(a, b):
    """Elementwise bucket addition of two trimmed count lists — the one
    merge the whole histogram plane uses (router fleet merge, ring
    window merge, the drill's window deltas)."""
    if len(a) < len(b):
        a, b = b, a
    out = list(a)
    for i, c in enumerate(b):
        out[i] += c
    return out


def merge_exemplars(a, b):
    """Merge two exemplar maps ({bucket_index: (trace_id, value,
    unix_ts)}), keeping the max-value sample per bucket — the same
    associative rule LogLinearHistogram uses, so fleet-merged windows
    still name a real trace per tail bucket."""
    out = {int(k): tuple(v) for k, v in a.items()}
    for k, ex in b.items():
        k = int(k)
        ex = tuple(ex)
        cur = out.get(k)
        if cur is None or ex[1] >= cur[1]:
            out[k] = ex
    return out


def _sub_exemplars(cur, base):
    """Exemplars NEW to this window: entries of `cur` absent from (or
    changed since) `base` — the exemplar twin of the bucket-delta
    subtraction, so a window only names traces recorded inside it."""
    out = {}
    for k, ex in cur.items():
        if tuple(base.get(k, ())) != tuple(ex):
            out[k] = tuple(ex)
    return out


def _sub_counts(cur, base):
    """Trimmed `cur - base` bucket deltas (cur is cumulative, so every
    delta is >= 0 for well-formed inputs; negative deltas clamp to 0 —
    a replaced replica's counter reset must not poison a window)."""
    out = []
    for i, c in enumerate(cur):
        b = base[i] if i < len(base) else 0
        out.append(max(0, c - b))
    while out and not out[-1]:
        out.pop()
    return out


def merge_window_deltas(a, b):
    """Merge two window-delta dicts (cross-replica aggregation):
    counter deltas add, histogram bucket deltas add elementwise, gauges
    add (fleet totals). Returns a new dict; inputs untouched."""
    out = {
        "t0": min(a.get("t0", 0.0), b.get("t0", 0.0)),
        "t1": max(a.get("t1", 0.0), b.get("t1", 0.0)),
        "counters": dict(a.get("counters", {})),
        "gauges": dict(a.get("gauges", {})),
        "hists": {k: list(v) for k, v in a.get("hists", {}).items()},
        "exemplars": {k: dict(v)
                      for k, v in a.get("exemplars", {}).items()},
    }
    for name, v in b.get("counters", {}).items():
        out["counters"][name] = out["counters"].get(name, 0) + v
    for name, v in b.get("gauges", {}).items():
        out["gauges"][name] = out["gauges"].get(name, 0) + v
    for name, counts in b.get("hists", {}).items():
        out["hists"][name] = add_counts(
            out["hists"].get(name, []), counts
        )
    for name, exes in b.get("exemplars", {}).items():
        out["exemplars"][name] = merge_exemplars(
            out["exemplars"].get(name, {}), exes
        )
    return out


class TimeSeriesRing(object):
    """Bounded ring of fixed-interval windows over cumulative inputs.

    `observe()` takes CUMULATIVE counter values and CUMULATIVE histogram
    bucket counts (plus last-value gauges); the ring differences them
    at window boundaries, so feeders never maintain deltas themselves
    and the invariant `sum of all window deltas (+ the open partial) ==
    latest cumulative` holds by construction — the property the
    snapshot()/close() window-boundary regression test pins.

    A window closes at the first observation at/past `interval_secs`
    since the window opened; windows carry explicit `t0`/`t1`, so a
    sparse feeder (an idle server) yields WIDER windows rather than
    fabricated empty ones, and horizon queries weigh them by real time.
    `flush()` force-closes the open partial window (shutdown path).
    """

    def __init__(self, interval_secs=1.0, capacity=240,
                 clock=time.monotonic):
        self.interval_secs = float(interval_secs)
        self.capacity = max(1, int(capacity))
        self._clock = clock
        self._windows = deque()
        self.dropped = 0  # closed windows evicted by the bound
        self._t0 = clock()
        self._base = {"counters": {}, "hists": {}, "exemplars": {}}
        self._last = {"counters": {}, "gauges": {}, "hists": {},
                      "exemplars": {}}
        self._seen = False  # any observation since the last close

    def due(self, now=None):
        """Cheap boundary check — feeders on hot paths call this before
        paying for an `observe` snapshot."""
        now = self._clock() if now is None else now
        return now - self._t0 >= self.interval_secs

    def observe(self, counters=None, gauges=None, hists=None,
                exemplars=None, now=None, roll=True):
        """One cumulative observation; closes the open window when the
        interval has elapsed (roll=True). Values are copied — callers
        may hand live dicts/lists. `exemplars` is {hist_name:
        {bucket_index: (trace_id, value, unix_ts)}} — the histogram's
        exemplars_wire() shape — differenced at window boundaries like
        the bucket counts."""
        now = self._clock() if now is None else now
        if counters:
            self._last["counters"].update(counters)
        if gauges:
            self._last["gauges"].update(gauges)
        if hists:
            for name, counts in hists.items():
                self._last["hists"][name] = list(counts)
        if exemplars:
            for name, exes in exemplars.items():
                self._last["exemplars"][name] = {
                    int(k): tuple(v) for k, v in exes.items()
                }
        self._seen = True
        if roll and now - self._t0 >= self.interval_secs:
            self._close(now)

    def rebase(self, now=None):
        """Restart the open window from the CURRENT cumulative state
        without emitting a window: the next close deltas against now,
        not against zero. The fleet collector's first scrape of a
        long-lived process calls this so lifetime totals never
        masquerade as a window's worth of traffic."""
        now = self._clock() if now is None else now
        self._base = {
            "counters": dict(self._last["counters"]),
            "hists": {k: list(v)
                      for k, v in self._last["hists"].items()},
            "exemplars": {k: dict(v)
                          for k, v in self._last["exemplars"].items()},
        }
        self._t0 = now
        self._seen = False

    def flush(self, now=None):
        """Force-close the open partial window (even shorter than the
        interval) so a process stopping mid-window loses nothing."""
        now = self._clock() if now is None else now
        if self._seen:
            self._close(now)

    def _close(self, now):
        base = self._base
        window = {
            "t0": self._t0,
            "t1": now,
            "counters": {
                name: v - base["counters"].get(name, 0)
                for name, v in self._last["counters"].items()
            },
            "gauges": dict(self._last["gauges"]),
            "hists": {
                name: _sub_counts(counts, base["hists"].get(name, []))
                for name, counts in self._last["hists"].items()
            },
            "exemplars": {
                name: _sub_exemplars(
                    exes, base["exemplars"].get(name, {})
                )
                for name, exes in self._last["exemplars"].items()
            },
        }
        self._windows.append(window)
        if len(self._windows) > self.capacity:
            self._windows.popleft()
            self.dropped += 1
        self._base = {
            "counters": dict(self._last["counters"]),
            "hists": {k: list(v)
                      for k, v in self._last["hists"].items()},
            "exemplars": {k: dict(v)
                          for k, v in self._last["exemplars"].items()},
        }
        self._t0 = now
        self._seen = False

    # -------------------------------------------------------- queries

    def windows(self, horizon_secs=None, now=None):
        """Closed windows, oldest first; with a horizon, only windows
        whose END falls inside the trailing horizon."""
        if horizon_secs is None:
            return list(self._windows)
        now = self._clock() if now is None else now
        cutoff = now - float(horizon_secs)
        return [w for w in self._windows if w["t1"] > cutoff]

    def sum_counter(self, name, horizon_secs=None, now=None):
        return sum(
            w["counters"].get(name, 0)
            for w in self.windows(horizon_secs, now)
        )

    def merged_hist_counts(self, name, horizon_secs=None, now=None):
        """Bucket-added histogram deltas over the trailing horizon —
        hand to LogLinearHistogram.from_counts for percentiles, or to
        the SLO engine for threshold counting."""
        out = []
        for w in self.windows(horizon_secs, now):
            counts = w["hists"].get(name)
            if counts:
                out = add_counts(out, counts)
        return out

    def merged_exemplars(self, name, horizon_secs=None, now=None):
        """Max-value-per-bucket exemplar merge over the trailing
        horizon — the traces the SLO engine's bad buckets can be
        joined back to."""
        out = {}
        for w in self.windows(horizon_secs, now):
            exes = w.get("exemplars", {}).get(name)
            if exes:
                out = merge_exemplars(out, exes)
        return out

    def pending_counter(self, name):
        """The open partial window's delta for one counter (live view;
        the window is not closed)."""
        return (self._last["counters"].get(name, 0)
                - self._base["counters"].get(name, 0))

    def baseline_counter(self, name):
        """The cumulative value the open window STARTED from — a
        feeder holding a fresher cumulative than the last observe()
        computes its own live partial as `live - baseline` (the
        telemetry hit-rate does)."""
        return self._base["counters"].get(name, 0)

    def latest(self):
        """Copies of the latest CUMULATIVE observation (counters,
        gauges, hists) — what an exposition renders when it wants
        lifetime values for series the ring is the only holder of
        (e.g. the router's fleet-merged replica histograms)."""
        return {
            "counters": dict(self._last["counters"]),
            "gauges": dict(self._last["gauges"]),
            "hists": {k: list(v)
                      for k, v in self._last["hists"].items()},
            "exemplars": {k: dict(v)
                          for k, v in self._last["exemplars"].items()},
        }


# ------------------------------------------------ Prometheus exposition


def _sanitize(name):
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalnum() or ch in "_:"
        if ok and not (i == 0 and ch.isdigit()):
            out.append(ch)
        else:
            out.append("_")
    return "".join(out)


def _fmt_value(v):
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return "%d" % int(v)
    return repr(v)


def _fmt_labels(labels):
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        val = str(labels[k]).replace("\\", "\\\\")
        val = val.replace('"', '\\"').replace("\n", "\\n")
        parts.append('%s="%s"' % (_sanitize(k), val))
    return "{%s}" % ",".join(parts)


def counter_family(name, help_text, value, labels=None):
    """A counter family with one sample. `name` must already end in
    `_total` (the naming rule the parser enforces)."""
    return (name, "counter", help_text, [("", labels or {}, value)])


def labeled_counter_family(name, help_text, samples):
    """A counter family with several labeled series (e.g. one
    slow-cause counter per `cause` label). `samples` =
    [(labels, value)]; `name` must end in `_total`."""
    return (name, "counter", help_text,
            [("", labels or {}, v) for labels, v in samples])


def gauge_family(name, help_text, samples):
    """`samples` = [(labels, value)] — one family may carry several
    labeled series (e.g. one burn-rate gauge per SLO x window)."""
    return (name, "gauge", help_text,
            [("", labels or {}, v) for labels, v in samples])


def hist_family(name, help_text, series):
    """A histogram family from trimmed log-linear bucket counts.

    `series` = [(labels, counts, sum_ms_or_None)] or
    [(labels, counts, sum_ms_or_None, exemplars)] — counts in the
    shared scheme's wire form, `exemplars` the histogram's
    {bucket_index: (trace_id, value, unix_ts)} map. Renders cumulative
    `_bucket` samples at every NON-EMPTY bucket's upper bound plus the
    mandatory `+Inf`, `_sum` (estimated from bucket midpoints when not
    supplied) and `_count`; a bucket with an exemplar renders it in
    OpenMetrics exemplar syntax after the sample value
    (``... # {trace_id="..."} 12.3 1722800000``). Subsetting the
    bounds is valid Prometheus — cumulative counts stay monotone, and
    the shared scheme makes any two expositions comparable
    bucket-for-bucket."""
    samples = []
    for entry in series:
        labels, counts, sum_ms = entry[0], entry[1], entry[2]
        exemplars = entry[3] if len(entry) > 3 else None
        cum = 0
        est_sum = 0.0
        for i, c in enumerate(counts):
            if i >= NUM_BUCKETS:
                break
            if not c:
                continue
            cum += c
            lo, hi = bucket_bounds(i)
            est_sum += (lo + hi) / 2.0 * c
            lab = dict(labels or {})
            lab["le"] = _fmt_value(hi)
            ex = (exemplars or {}).get(i)
            if ex is not None:
                tid, value, ts = ex
                samples.append(("_bucket", lab, cum,
                                (str(tid), float(value), float(ts))))
            else:
                samples.append(("_bucket", lab, cum))
        lab = dict(labels or {})
        lab["le"] = "+Inf"
        samples.append(("_bucket", lab, cum))
        samples.append(("_sum", dict(labels or {}),
                        est_sum if sum_ms is None else sum_ms))
        samples.append(("_count", dict(labels or {}), cum))
    return (name, "histogram", help_text, samples)


def render_prometheus(families):
    """Prometheus text format 0.0.4 from [(name, type, help, samples)]
    families; samples are [(suffix, labels, value)] or — on histogram
    `_bucket` lines only — [(suffix, labels, value, (trace_id,
    ex_value, ex_unix_ts))], rendered as an OpenMetrics exemplar."""
    lines = []
    for name, mtype, help_text, samples in families:
        base = _sanitize(name)
        lines.append("# HELP %s %s" % (
            base,
            str(help_text).replace("\\", "\\\\").replace("\n", "\\n"),
        ))
        lines.append("# TYPE %s %s" % (base, mtype))
        for sample in samples:
            suffix, labels, value = sample[0], sample[1], sample[2]
            line = "%s%s%s %s" % (
                base, _sanitize(suffix) if suffix else "",
                _fmt_labels(labels), _fmt_value(value),
            )
            if len(sample) > 3 and sample[3] is not None:
                tid, ex_value, ex_ts = sample[3]
                line += " # %s %s %s" % (
                    _fmt_labels({"trace_id": tid}),
                    _fmt_value(ex_value), _fmt_value(ex_ts),
                )
            lines.append(line)
    return "\n".join(lines) + "\n"


class MetricsServer(object):
    """`GET /metrics` over stdlib http.server on a daemon thread.

    `collect` returns the families to render (called per scrape, on
    the HTTP thread — collectors lock themselves). Off by default
    everywhere; entrypoints arm it via --metrics_port /
    EDL_METRICS_PORT. Binds host 0.0.0.0 so a scraper on another host
    reaches it; port 0 = ephemeral (the bound port is `self.port`)."""

    def __init__(self, collect, port=0, host="0.0.0.0"):
        import http.server
        import socketserver

        self._collect = collect
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404)
                    return
                try:
                    body = render_prometheus(
                        outer._collect()
                    ).encode("utf-8")
                except Exception as e:  # noqa: BLE001 - scrape = 500
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *_args):
                pass  # scrapes must not spam the serving logs

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, int(port)), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="metrics-exposition",
        )
        self._thread.start()

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
