"""Per-request cause attribution: WHY was this request slow?

The metrics plane (PR 12) can say *that* TTFT p99 is burning its SLO
budget; the span tree (PR 6) records *what happened* to one request.
This module closes the gap with a pure function over a request's span
tree: `attribute(spans)` folds the tree's timestamps and machine-
readable event attrs into an ordered latency breakdown and ONE
dominant-cause label an operator (or the fleet collector / the
replica's `slow_cause` counter family) can act on.

The cause taxonomy (closed set — every consumer keys on it):

    queue_wait              admitted but not seated, NET of the time
                            another request's prefill held the
                            scheduler (see below) — pure backlog
    dispatch_retries        router-side time before the WINNING
                            dispatch leg began (failed legs, breaker
                            cooldowns, backoff sleeps, hedging)
    prefill_own             this request's own prefill / shared-suffix
                            tile compute (seated -> first token),
                            minus any revival upload
    prefill_blocked_by_other  time the request sat admitted-but-
                            unstepped while ANOTHER slot's prefill or
                            suffix tile ran — the prefill-
                            monopolization signal the chunked-prefill
                            scheduler item needs quantified. Derived
                            from the scheduler's cumulative prefill-
                            busy clock stamped at admission and read
                            at seating (`prefill_blocked_ms` on the
                            `seated`/`expired` events).
    revive_upload           host->device revival of a spilled prefix
                            chain at seat time (tiered KV)
    decode                  first token -> terminal on the replica
    stream_stall            the winning dispatch leg's duration beyond
                            the replica's serve span — transport and
                            handler-side stalls around the tokens

Inputs are plain span DICTS (`Span.to_dict()` / the dump tool's merge
output) — the function never touches live recorder state, so it runs
identically in the replica process (over the request's own serve
span), in the bench (over in-process trees) and in the fleet collector
(over `$EDL_TRACE_DIR` exports). Missing evidence degrades, never
raises: components without events report 0.0 ms and
`evidence_complete` goes False, so a verdict over a partial trace says
so instead of presenting itself as the whole story.
"""

#: the closed cause set, in causal order (admission -> stream). The
#: replica's slow_cause counter family, EDL401's declared union, the
#: collector's cause histogram and the bench tail_report all key on
#: EXACTLY these names.
CAUSES = ("queue_wait", "dispatch_retries", "prefill_own",
          "prefill_blocked_by_other", "revive_upload", "decode",
          "stream_stall")

#: a completed request is "terminally slow" when it consumed at least
#: this fraction of its own deadline budget (the replica's deadline is
#: the classifier — no new config surface); breaches/errors always are
SLOW_DEADLINE_FRACTION = 0.8

#: root-span statuses that are slow/failed by definition
_BAD_STATUSES = ("DEADLINE_EXCEEDED",)


def is_terminally_slow(status, e2e_ms, deadline_ms):
    """The replica-side slow classifier: a deadline breach is slow, an
    error is not (it is FAST and wrong — a different counter), and a
    completed request is slow when it burned >= SLOW_DEADLINE_FRACTION
    of its own deadline budget. No deadline => never classified (the
    fleet-wide SLOs own that story through the collector)."""
    if status in _BAD_STATUSES:
        return True
    if not deadline_ms or deadline_ms <= 0:
        return False
    return (status == "ok"
            and e2e_ms >= SLOW_DEADLINE_FRACTION * deadline_ms)


def _events(span):
    """{name: [(ts, attrs)...]} for one span dict."""
    out = {}
    for ev in span.get("events", ()):
        out.setdefault(ev["name"], []).append(
            (ev["ts"], ev.get("attrs", {}))
        )
    return out


def _span_ms(span):
    end = span.get("end")
    start = span.get("start")
    if end is None or start is None:
        return None
    return max(0.0, (end - start) * 1000.0)


def _pick_root(spans):
    """The request root: a router_generate[_stream] span when the tree
    has one, else the serve span, else the earliest span."""
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans
             if not s.get("parent_span_id")
             or s["parent_span_id"] not in ids]
    for pool in (roots, spans):
        for name in ("router_generate", "router_generate_stream"):
            named = [s for s in pool if s["name"] == name]
            if named:
                return min(named, key=lambda s: s["start"])
        named = [s for s in pool if s["name"] == "serve"]
        if named:
            return min(named, key=lambda s: s["start"])
    return min(spans, key=lambda s: s["start"])


def _pick_serve(spans):
    """The serve span that carried the answer: prefer status ok, then
    the LATEST by start (a re-dispatched request's earlier serve legs
    failed)."""
    serves = [s for s in spans if s["name"] == "serve"]
    if not serves:
        return None
    ok = [s for s in serves if s.get("status") == "ok"]
    pool = ok or serves
    return max(pool, key=lambda s: s["start"])


def _winning_dispatch(spans, serve):
    """The dispatch leg the answering serve span rode under (matched
    by parent id), else the last ok dispatch, else None."""
    dispatches = [s for s in spans if s["name"] == "dispatch"]
    if not dispatches:
        return None
    if serve is not None:
        for d in dispatches:
            if serve.get("parent_span_id") == d["span_id"]:
                return d
    ok = [d for d in dispatches if d.get("status") == "ok"]
    pool = ok or dispatches
    return max(pool, key=lambda s: s["start"])


def attribute(spans):
    """Fold ONE request's span dicts into the ordered cause breakdown.

    Returns::

        {"trace_id": ..., "status": <root status>,
         "total_ms": <root duration>,
         "breakdown": [{"cause": c, "ms": x} for c in CAUSES],
         "dominant_cause": <argmax cause>, "dominant_ms": x,
         "evidence_complete": bool}

    Pure and total: any span subset yields a verdict; thin evidence
    zeroes components and clears `evidence_complete`.
    """
    if not spans:
        return {
            "trace_id": None, "status": None, "total_ms": 0.0,
            "breakdown": [{"cause": c, "ms": 0.0} for c in CAUSES],
            "dominant_cause": None, "dominant_ms": 0.0,
            "evidence_complete": False,
        }
    root = _pick_root(spans)
    serve = _pick_serve(spans)
    win = _winning_dispatch(spans, serve)
    ms = {c: 0.0 for c in CAUSES}
    complete = True

    total_ms = _span_ms(root)
    if total_ms is None:
        total_ms = 0.0
        complete = False

    if win is not None:
        # time the router burned before the winning leg started:
        # failed legs, breaker cooldowns, full-jitter backoff
        ms["dispatch_retries"] = max(
            0.0, (win["start"] - root["start"]) * 1000.0
        )

    if serve is None:
        complete = False
    else:
        ev = _events(serve)
        serve_ms = _span_ms(serve) or 0.0
        queued = ev.get("queued")
        seated = ev.get("seated")
        expired = ev.get("expired")
        first = ev.get("first_token")
        # queue wait: queued -> seated (or -> span end for a request
        # that expired in the queue), split into pure backlog vs time
        # another slot's prefill held the single-threaded scheduler
        if queued:
            q_ts = queued[0][0]
            if seated:
                s_ts, s_attrs = seated[0]
                wait_ms = max(0.0, (s_ts - q_ts) * 1000.0)
                blocked = float(s_attrs.get("prefill_blocked_ms", 0.0))
            elif expired or serve.get("end") is not None:
                end_ts = (expired[0][0] if expired
                          else serve["end"])
                wait_ms = max(0.0, (end_ts - q_ts) * 1000.0)
                blocked = float(
                    (expired[0][1] if expired else {})
                    .get("prefill_blocked_ms", 0.0)
                )
            else:
                wait_ms, blocked = 0.0, 0.0
                complete = False
            blocked = min(blocked, wait_ms)
            ms["prefill_blocked_by_other"] = blocked
            ms["queue_wait"] = wait_ms - blocked
        else:
            complete = False
        for _ts, attrs in ev.get("revive_upload", ()):
            ms["revive_upload"] += float(attrs.get("ms", 0.0))
        if seated and first:
            ms["prefill_own"] = max(
                0.0,
                (first[0][0] - seated[0][0]) * 1000.0
                - ms["revive_upload"],
            )
        if first and serve.get("end") is not None:
            ms["decode"] = max(
                0.0, (serve["end"] - first[0][0]) * 1000.0
            )
        elif seated and not first:
            complete = False
        if win is not None:
            win_ms = _span_ms(win)
            if win_ms is not None:
                ms["stream_stall"] = max(0.0, win_ms - serve_ms)

    dominant = max(CAUSES, key=lambda c: ms[c])
    return {
        "trace_id": root.get("trace_id"),
        "status": root.get("status"),
        "total_ms": round(total_ms, 3),
        "breakdown": [
            {"cause": c, "ms": round(ms[c], 3)} for c in CAUSES
        ],
        "dominant_cause": dominant if ms[dominant] > 0.0 else None,
        "dominant_ms": round(ms[dominant], 3),
        "evidence_complete": complete,
    }


def cause_histogram(verdicts):
    """{cause: count} over a batch of attribute() verdicts (None
    dominants — no measurable component — are skipped): the
    "distribution of why" the bench tail_report and the collector
    report record."""
    out = {}
    for v in verdicts:
        cause = v.get("dominant_cause")
        if cause:
            out[cause] = out.get(cause, 0) + 1
    return out
