"""Fleet collector: scrape /metrics fleet-wide, re-evaluate the SLOs,
and join burning windows to the traces that explain them.

    python -m elasticdl_tpu.observability.collector \\
        --endpoints 127.0.0.1:9100,127.0.0.1:9101 \\
        --scrapes 3 --interval 2 \\
        --trace_dir /tmp/edl-traces --out INCIDENT_REPORT.json

The missing loop this closes: the metrics plane (PR 12) says *that* an
SLO is burning, the span recorder (PR 6) knows *what happened* to each
request — but an operator staring at a burning `edl_router_slo_burn`
gauge had no path from the gauge to one concrete slow request. The
collector walks that path end to end, as a standalone process with no
privileged access — everything it knows comes through the same
`/metrics` text any Prometheus scraper reads (validated by the
INDEPENDENT parser in observability/promparse.py, never the renderer)
and the span exports under ``$EDL_TRACE_DIR``:

1. **Scrape**: each endpoint's ``/metrics``, ``--scrapes`` rounds,
   ``--interval`` seconds apart; every page must parse CLEAN.
2. **Merge**: per round, counters add and histogram buckets add across
   endpoints (the same bucket-addition form the router uses for fleet
   percentiles — never averages); exemplars merge max-value-per-bucket.
3. **Window**: the merged cumulative rounds feed a TimeSeriesRing
   (rebased on the first round, so lifetime totals never masquerade as
   a window), giving true between-scrape deltas.
4. **Re-evaluate**: the PR 12 BurnRateEngine runs the DECLARED
   objectives (CLI flags mirroring RouterConfig.slo_*, or — with
   ``--router`` — the live declarations from `router_status.slo`)
   over those windows, fleet-wide.
5. **Join**: each latency objective's above-threshold buckets are
   joined to the exemplars scraped off them — trace ids with values
   and timestamps, the metrics→traces edge.
6. **Attribute**: exemplar traces found in the ``--trace_dir`` span
   exports run through forensics.attribute(); their dominant causes
   histogram into the incident's "distribution of why".
7. **Report**: one self-contained JSON document (+ rendered text) —
   the artifact the autoscale/chaos drills archive; `validate_report`
   is the schema gate the drill asserts through. The report carries
   the recorders' drop counters per service, so a verdict over
   incomplete evidence SAYS so instead of posing as the whole story.

Scrape either the router OR the replicas, not both: the router's
fleet-merged histograms already contain its replicas' buckets, and
double-scraping would double-count.
"""

import argparse
import json
import os
import sys
import time
import urllib.request

from elasticdl_tpu.observability import forensics
from elasticdl_tpu.observability.dump import drops_by_service, merge_dir
from elasticdl_tpu.observability.histogram import (
    NUM_BUCKETS,
    LogLinearHistogram,
    bucket_bounds,
    bucket_index,
)
from elasticdl_tpu.observability.metrics import (
    TimeSeriesRing,
    add_counts,
    merge_exemplars,
)
from elasticdl_tpu.observability.promparse import parse_prometheus_text
from elasticdl_tpu.observability.slo import (
    BurnRateEngine,
    SloSpec,
    default_router_slos,
)
from elasticdl_tpu.observability.tracing import TRACE_DIR_ENV, group_by_trace

REPORT_SCHEMA = "edl-incident-report/1"

#: upper bucket bound (as the renderer formats it, re-parsed to float)
#: -> bucket index: exact float equality holds because both sides
#: compute the same bound from the same shared scheme
_LE_TO_IDX = {bucket_bounds(i)[1]: i for i in range(NUM_BUCKETS)}

#: scraped family name -> the ring-histogram name the declared SLOs
#: read. Replica TTFT and the router's fleet merge are the SAME series
#: fleet-wide, so both map onto fleet_ttft_ms.
_HIST_ALIASES = {
    "edl_serving_ttft_ms": "fleet_ttft_ms",
    "edl_router_fleet_ttft_ms": "fleet_ttft_ms",
    "edl_serving_queue_wait_ms": "fleet_queue_wait_ms",
    "edl_router_fleet_queue_wait_ms": "fleet_queue_wait_ms",
    "edl_serving_e2e_ms": "e2e_ms",
    "edl_router_e2e_ms": "e2e_ms",
    "edl_serving_step_ms": "step_ms",
}


def default_fetch(endpoint, timeout=10.0):
    """GET an endpoint's /metrics page. `endpoint` is host:port or a
    full URL; returns the exposition text."""
    url = endpoint
    if "://" not in url:
        url = "http://%s" % url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    return urllib.request.urlopen(url, timeout=timeout).read().decode(
        "utf-8"
    )


def _counts_from_hist_family(info):
    """Trimmed shared-scheme bucket counts from one parsed histogram
    family (all series of the family summed — labels like `phase`
    collapse into the fleet view), plus the family's exemplars mapped
    to bucket indices."""
    counts = []
    series = {}
    for name, labels, value in info["samples"]:
        if not name.endswith("_bucket") or "le" not in labels:
            continue
        key = tuple(sorted(
            (k, v) for k, v in labels.items() if k != "le"
        ))
        series.setdefault(key, []).append(
            (float("inf") if labels["le"] == "+Inf"
             else float(labels["le"]), value)
        )
    for buckets in series.values():
        buckets.sort(key=lambda p: p[0])
        dense = [0] * NUM_BUCKETS
        prev = 0.0
        for le, cum in buckets:
            delta = cum - prev
            prev = cum
            if delta <= 0:
                continue
            idx = _LE_TO_IDX.get(le)
            if idx is None:
                # not a shared-scheme bound (+Inf tail or a foreign
                # exposition): clamp into the top bucket
                idx = NUM_BUCKETS - 1
            dense[idx] += int(delta)
        counts = add_counts(counts, _trim(dense))
    exemplars = {}
    for _name, labels, ex_labels, value, ts in info.get(
            "exemplars", ()):
        tid = ex_labels.get("trace_id")
        if not tid:
            continue
        idx = _LE_TO_IDX.get(
            float("inf") if labels.get("le") == "+Inf"
            else float(labels.get("le", "inf")),
            NUM_BUCKETS - 1,
        )
        exemplars = merge_exemplars(
            exemplars,
            {idx: (tid, float(value),
                   float(ts) if ts is not None else 0.0)},
        )
    return counts, exemplars


def _trim(dense):
    last = 0
    for i, c in enumerate(dense):
        if c:
            last = i + 1
    return dense[:last]


def _observation_from_page(families):
    """(counters, hists, exemplars) in ring shape from one parsed
    exposition: counter families lose their `edl_<svc>_`/`_total`
    affixes (labeled counters key as name.label_value), histogram
    families map through _HIST_ALIASES."""
    counters, hists, exemplars = {}, {}, {}
    for fam, info in families.items():
        if info["type"] == "counter":
            base = fam
            if base.endswith("_total"):
                base = base[:-len("_total")]
            for prefix in ("edl_serving_", "edl_router_",
                           "edl_autoscaler_", "edl_master_"):
                if base.startswith(prefix):
                    base = base[len(prefix):]
                    break
            for _name, labels, value in info["samples"]:
                key = base
                if labels:
                    key = "%s.%s" % (base, ".".join(
                        str(labels[k]) for k in sorted(labels)
                    ))
                counters[key] = counters.get(key, 0) + value
        elif info["type"] == "histogram":
            name = _HIST_ALIASES.get(fam)
            if name is None:
                continue
            counts, exes = _counts_from_hist_family(info)
            hists[name] = add_counts(hists.get(name, []), counts)
            if exes:
                exemplars[name] = merge_exemplars(
                    exemplars.get(name, {}), exes
                )
    return counters, hists, exemplars


def _merge_observations(obs):
    """Fleet merge of per-endpoint observations for one round:
    counters add, buckets add, exemplars keep max-value-per-bucket."""
    counters, hists, exemplars = {}, {}, {}
    for c, h, e in obs:
        for k, v in c.items():
            counters[k] = counters.get(k, 0) + v
        for k, v in h.items():
            hists[k] = add_counts(hists.get(k, []), v)
        for k, v in e.items():
            exemplars[k] = merge_exemplars(exemplars.get(k, {}), v)
    return counters, hists, exemplars


def scrape_fleet(endpoints, scrapes=2, interval_secs=2.0,
                 fetch=default_fetch, sleep=time.sleep,
                 clock=time.monotonic):
    """Scrape every endpoint `scrapes` times, `interval_secs` apart.
    Every page must parse through the independent parser (a violation
    raises — a scrape is a pass/fail check). Returns the serializable
    scrape BUNDLE that build_report later turns into the incident
    report, so scraping (mid-incident) and trace joining (after spans
    export) can happen at different times."""
    if scrapes < 2:
        raise ValueError(
            "scrapes must be >= 2 — burn rates need at least one "
            "between-scrape window, got %d" % scrapes
        )
    rounds = []
    for n in range(int(scrapes)):
        if n:
            sleep(interval_secs)
        at = clock()
        per_endpoint = []
        for ep in endpoints:
            families = parse_prometheus_text(fetch(ep))
            per_endpoint.append(
                (ep, _observation_from_page(families),
                 len(families))
            )
        counters, hists, exemplars = _merge_observations(
            [o for _ep, o, _n in per_endpoint]
        )
        rounds.append({
            "at": at,
            "unix": time.time(),
            "families": {ep: n for ep, _o, n in per_endpoint},
            "counters": counters,
            "hists": hists,
            "exemplars": {
                name: {str(k): list(v) for k, v in exes.items()}
                for name, exes in exemplars.items()
            },
        })
    return {
        "endpoints": list(endpoints),
        "interval_secs": float(interval_secs),
        "rounds": rounds,
    }


def specs_from_flags(args):
    """The declared objectives, from CLI flags mirroring
    RouterConfig.slo_* defaults."""
    return default_router_slos(
        args.slo_ttft_p99_ms, args.slo_e2e_p99_ms,
        args.slo_goodput_goal, latency_goal=args.slo_latency_goal,
    )


def specs_from_router(address, timeout=10.0):
    """The declared objectives straight from a live router's
    router_status.slo blocks — the same declarations its own burn
    engine evaluates. Returns ([SloSpec], replica_addresses)."""
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel

    stub = RouterStub(build_channel(address))
    status = stub.router_status(pb.RouterStatusRequest(),
                                timeout=timeout)
    specs = []
    for blk in status.slo:
        if blk.kind == "latency":
            specs.append(SloSpec(
                blk.name, "latency", blk.goal,
                hist=("e2e_ms" if blk.name.startswith("e2e")
                      else "fleet_ttft_ms"),
                threshold_ms=blk.threshold_ms,
            ))
        else:
            specs.append(SloSpec(
                blk.name, "availability", blk.goal,
                bad_counters=("shed", "errors"),
                total_counters=("routed",),
            ))
    return specs, [r.address for r in status.replica]


def _ring_from_bundle(bundle):
    """Replay the bundle's merged rounds into a TimeSeriesRing: the
    first round REBASES (a long-lived process's lifetime totals are
    not a window), every later round closes one true delta window."""
    rounds = bundle["rounds"]
    interval = bundle["interval_secs"]
    ring = TimeSeriesRing(
        interval_secs=max(1e-9, interval * 0.5),
        capacity=max(16, len(rounds) + 1),
        clock=lambda: rounds[0]["at"],
    )
    for n, rnd in enumerate(rounds):
        exemplars = {
            name: {int(k): tuple(v) for k, v in exes.items()}
            for name, exes in rnd.get("exemplars", {}).items()
        }
        ring.observe(counters=rnd["counters"], hists=rnd["hists"],
                     exemplars=exemplars, now=rnd["at"],
                     roll=n > 0)
        if n == 0:
            ring.rebase(now=rnd["at"])
    return ring


def build_report(bundle, specs, trace_dir=None,
                 fast_windows=1, slow_windows=None):
    """The incident report from a scrape bundle: re-run the burn
    engine fleet-wide over the bundle's windows, join latency
    objectives to their scraped exemplars, pull those traces from
    `trace_dir`'s span exports, attribute each, and histogram the
    dominant causes. Pure given the bundle (no network)."""
    rounds = bundle["rounds"]
    interval = bundle["interval_secs"]
    ring = _ring_from_bundle(bundle)
    now = rounds[-1]["at"]
    n_windows = len(rounds) - 1
    if slow_windows is None:
        slow_windows = n_windows
    # horizons in whole scrape intervals: real inter-scrape gaps run a
    # hair OVER the nominal interval (sleep + scrape time), so a
    # horizon of exactly k*interval selects the last k windows
    engine = BurnRateEngine(
        specs,
        fast_window_secs=interval * fast_windows,
        slow_window_secs=interval * slow_windows,
    )
    slo_reports = engine.evaluate(ring, now=now)
    alerting = [r["name"] for r in slo_reports if r["alerting"]]

    # ---- metrics -> traces: exemplars per latency objective
    exemplar_rows = []
    for spec in specs:
        if spec.kind != "latency":
            continue
        exes = ring.merged_exemplars(spec.hist, now=now)
        # the latest cumulative state also carries exemplars the
        # rebase filtered out of windows (recorded before round 0) —
        # they still name real traces, flagged as pre-window
        cumulative = ring.latest()["exemplars"].get(spec.hist, {})
        cut = bucket_index(spec.threshold_ms)
        seen = set()
        for source, exmap in (("window", exes),
                              ("cumulative", cumulative)):
            for idx, (tid, value, ts) in sorted(exmap.items()):
                if (tid, idx) in seen:
                    continue
                seen.add((tid, idx))
                exemplar_rows.append({
                    "slo": spec.name,
                    "hist": spec.hist,
                    "bucket": idx,
                    "bucket_le_ms": bucket_bounds(idx)[1],
                    "trace_id": tid,
                    "value_ms": value,
                    "unix_ts": ts,
                    "source": source,
                    "above_threshold": idx > cut,
                })

    # ---- pull + attribute the exemplar traces
    traces = {}
    cause_counts = {}
    span_evidence = {
        "trace_dir": trace_dir or "",
        "exports": 0,
        "unreadable": 0,
        "drops_by_service": {},
        "complete": True,
    }
    if trace_dir:
        spans, meta = merge_dir(trace_dir)
        by_trace = group_by_trace(spans)
        span_evidence["exports"] = sum(
            1 for m in meta if "error" not in m
        )
        span_evidence["unreadable"] = sum(
            1 for m in meta if "error" in m
        )
        drops = drops_by_service(meta)
        span_evidence["drops_by_service"] = drops
        span_evidence["complete"] = (
            not drops and not span_evidence["unreadable"]
        )
        verdicts = []
        for row in exemplar_rows:
            tid = row["trace_id"]
            if tid in traces or tid not in by_trace:
                continue
            verdict = forensics.attribute(by_trace[tid])
            verdicts.append(verdict)
            traces[tid] = {
                "spans": len(by_trace[tid]),
                "services": sorted(
                    {s["service"] for s in by_trace[tid]}
                ),
                "attribution": verdict,
            }
        cause_counts = forensics.cause_histogram(verdicts)
    for row in exemplar_rows:
        row["resolved"] = row["trace_id"] in traces

    dominant = (max(cause_counts, key=cause_counts.get)
                if cause_counts else None)
    report = {
        "schema": REPORT_SCHEMA,
        "generated_unix": time.time(),
        "endpoints": bundle["endpoints"],
        "scrapes": len(rounds),
        "interval_secs": interval,
        "slo": slo_reports,
        "alerting": alerting,
        "exemplars": exemplar_rows,
        "traces": traces,
        "cause_histogram": cause_counts,
        "dominant_cause": dominant,
        "span_evidence": span_evidence,
    }
    return report


def validate_report(report):
    """Schema gate for the incident report (the drill asserts through
    it): raises ValueError on any violation, returns the report."""
    def need(cond, msg):
        if not cond:
            raise ValueError("incident report: %s" % msg)

    need(isinstance(report, dict), "not a dict")
    need(report.get("schema") == REPORT_SCHEMA,
         "schema is %r, want %r" % (report.get("schema"),
                                    REPORT_SCHEMA))
    for key in ("generated_unix", "endpoints", "scrapes",
                "interval_secs", "slo", "alerting", "exemplars",
                "traces", "cause_histogram", "span_evidence"):
        need(key in report, "missing key %r" % key)
    need(report["scrapes"] >= 2, "fewer than 2 scrapes")
    for r in report["slo"]:
        for k in ("name", "kind", "fast_burn", "slow_burn",
                  "alerting"):
            need(k in r, "slo entry missing %r" % k)
        need(r["fast_burn"] == r["fast_burn"]
             and abs(r["fast_burn"]) != float("inf"),
             "non-finite fast burn on %r" % r["name"])
        need(r["slow_burn"] == r["slow_burn"]
             and abs(r["slow_burn"]) != float("inf"),
             "non-finite slow burn on %r" % r["name"])
    for row in report["exemplars"]:
        for k in ("slo", "hist", "trace_id", "value_ms", "bucket",
                  "resolved"):
            need(k in row, "exemplar row missing %r" % k)
        need(bool(row["trace_id"]), "exemplar without trace_id")
    for tid, entry in report["traces"].items():
        need("attribution" in entry,
             "trace %s has no attribution" % tid)
        verdict = entry["attribution"]
        need(verdict.get("dominant_cause") is None
             or verdict["dominant_cause"] in forensics.CAUSES,
             "trace %s: unknown dominant cause %r"
             % (tid, verdict.get("dominant_cause")))
    for cause in report["cause_histogram"]:
        need(cause in forensics.CAUSES,
             "unknown cause %r in cause_histogram" % cause)
    ev = report["span_evidence"]
    for k in ("exports", "unreadable", "drops_by_service",
              "complete"):
        need(k in ev, "span_evidence missing %r" % k)
    return report


def render_text(report):
    """The human-readable incident summary next to the JSON."""
    lines = []
    lines.append("EDL INCIDENT REPORT (%s)" % report["schema"])
    lines.append("generated: %s" % time.strftime(
        "%Y-%m-%d %H:%M:%S UTC",
        time.gmtime(report["generated_unix"]),
    ))
    lines.append("endpoints: %s  (%d scrapes, %.1fs apart)"
                 % (", ".join(report["endpoints"]),
                    report["scrapes"], report["interval_secs"]))
    lines.append("")
    lines.append("SLO burn (fleet-wide re-evaluation):")
    for r in report["slo"]:
        flag = "  ALERTING" if r["alerting"] else ""
        lines.append(
            "  %-12s %-13s fast=%-8.3f slow=%-8.3f goal=%.3g%s"
            % (r["name"], r["kind"], r["fast_burn"], r["slow_burn"],
               r["goal"], flag)
        )
    lines.append("")
    n_above = sum(1 for e in report["exemplars"]
                  if e["above_threshold"])
    lines.append("exemplars: %d scraped (%d above an SLO threshold, "
                 "%d resolved to traces)"
                 % (len(report["exemplars"]), n_above,
                    sum(1 for e in report["exemplars"]
                        if e["resolved"])))
    for e in report["exemplars"][:10]:
        lines.append(
            "  [%s] %s=%.1f ms trace=%s%s%s"
            % (e["slo"], e["hist"], e["value_ms"], e["trace_id"],
               " >thr" if e["above_threshold"] else "",
               " (resolved)" if e["resolved"] else " (no spans)")
        )
    lines.append("")
    if report["cause_histogram"]:
        total = sum(report["cause_histogram"].values())
        lines.append("cause attribution over %d exemplar traces "
                     "(dominant: %s):"
                     % (total, report["dominant_cause"]))
        for cause in forensics.CAUSES:
            n = report["cause_histogram"].get(cause, 0)
            if n:
                lines.append("  %-26s %3d  (%.0f%%)"
                             % (cause, n, 100.0 * n / total))
    else:
        lines.append("cause attribution: no exemplar trace resolved "
                     "in the span exports")
    ev = report["span_evidence"]
    if ev["complete"]:
        lines.append("evidence: complete (%d exports, zero recorder "
                     "drops)" % ev["exports"])
    else:
        lines.append(
            "evidence: INCOMPLETE — %d unreadable exports, drops: %s"
            % (ev["unreadable"], ev["drops_by_service"] or "{}")
        )
    return "\n".join(lines) + "\n"


def percentile_of_counts(counts, q):
    """Convenience for report consumers: percentile over trimmed
    shared-scheme counts (the one histogram definition)."""
    return LogLinearHistogram.from_counts(counts).percentile(q)


def main(argv=None):
    # SIGUSR2 -> all-thread stack dump: a long-running collector is a
    # fleet process like any other — interrogable without killing it
    from elasticdl_tpu.observability.runtime_health import (
        install_sigusr2_dump,
    )

    install_sigusr2_dump()
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--endpoints", default="",
        help="comma-separated /metrics endpoints (host:port or URL); "
             "scrape the router OR the replicas, not both",
    )
    parser.add_argument(
        "--router", default="",
        help="router gRPC address: pull the DECLARED SLO objectives "
             "from router_status.slo instead of the --slo_* flags",
    )
    parser.add_argument("--scrapes", type=int, default=3)
    parser.add_argument("--interval", type=float, default=2.0)
    parser.add_argument(
        "--trace_dir", default=os.environ.get(TRACE_DIR_ENV, ""),
        help="span-export directory (default: $EDL_TRACE_DIR); empty "
             "= skip the trace join",
    )
    parser.add_argument("--out", default="INCIDENT_REPORT.json")
    parser.add_argument(
        "--text", default="",
        help="also write the rendered text summary here",
    )
    # declared objectives (defaults mirror RouterConfig.slo_*)
    parser.add_argument("--slo_ttft_p99_ms", type=float,
                        default=30000.0)
    parser.add_argument("--slo_e2e_p99_ms", type=float,
                        default=60000.0)
    parser.add_argument("--slo_latency_goal", type=float, default=0.01)
    parser.add_argument("--slo_goodput_goal", type=float, default=0.02)
    args = parser.parse_args(argv)

    endpoints = [e.strip() for e in args.endpoints.split(",")
                 if e.strip()]
    if not endpoints:
        print("collector: no --endpoints given", file=sys.stderr)
        return 2
    if args.router:
        specs, replicas = specs_from_router(args.router)
        print("collector: %d declared objectives from router %s "
              "(%d replicas registered)"
              % (len(specs), args.router, len(replicas)))
    else:
        specs = specs_from_flags(args)
    bundle = scrape_fleet(endpoints, scrapes=args.scrapes,
                          interval_secs=args.interval)
    report = build_report(bundle, specs,
                          trace_dir=args.trace_dir or None)
    validate_report(report)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    text = render_text(report)
    if args.text:
        with open(args.text, "w") as f:
            f.write(text)
    print(text, end="")
    print("collector: report -> %s" % args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
