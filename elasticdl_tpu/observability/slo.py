"""SLO burn-rate engine over the windowed time-series ring.

An SLO here is a declared objective plus an ERROR BUDGET: "TTFT p99
under 500 ms" really means "at most `goal` (e.g. 1%) of requests may
exceed 500 ms" — the 1% is budget that traffic is allowed to spend.
The **burn rate** of a window is how fast the budget is being spent
relative to plan:

    burn = bad_fraction(window) / goal

burn == 1.0 spends the budget exactly on schedule; burn == 10 exhausts
a 30-day budget in 3 days. Following the multi-window practice (Google
SRE workbook; the parameter-service elasticity literature uses the
same shape for scaling signals), each objective is evaluated over TWO
trailing horizons of the ring — a FAST window that reacts to an
incident and a SLOW window that filters blips — and `alerting` is true
only when BOTH burn above 1.0: fast-only is a spike, slow-only is old
news.

Two objective kinds, both computable from ring window deltas alone:

* **latency** — bad = samples in histogram buckets strictly above the
  threshold's bucket (within the shared log-linear scheme's ≤3.1%
  bucket resolution — the same tolerance every percentile in the
  system carries); total = all samples in the horizon.
* **availability** — bad = sum of the declared bad-counter deltas,
  total = sum of the total-counter deltas (e.g. shed+errors over
  routed: the goodput floor).

Burn rates are SIGNALS, not actions: the router surfaces them in
`router_status` (SloObjective blocks) and /metrics
(`edl_router_slo_burn`), and the autoscaler logs them as a read-only
advisory next to its queue-wait policy — the scaling decision itself
stays where PR 9 put it until the burn signal has earned trust in
drills.
"""

from elasticdl_tpu.observability.histogram import bucket_index


class SloSpec(object):
    """One declared objective. kind "latency" needs `hist` (the ring
    histogram name) + `threshold_ms`; kind "availability" needs
    `bad_counters` + `total_counters`. `goal` is the allowed bad
    fraction (the error budget), always > 0."""

    KINDS = ("latency", "availability")

    def __init__(self, name, kind, goal, hist=None, threshold_ms=None,
                 bad_counters=(), total_counters=()):
        if kind not in self.KINDS:
            raise ValueError("unknown SLO kind %r" % (kind,))
        if not 0.0 < float(goal) < 1.0:
            raise ValueError("goal must be in (0, 1), got %r" % (goal,))
        if kind == "latency" and (not hist or threshold_ms is None):
            raise ValueError(
                "latency SLO %r needs hist + threshold_ms" % name
            )
        if kind == "availability" and (
                not bad_counters or not total_counters):
            raise ValueError(
                "availability SLO %r needs bad/total counters" % name
            )
        self.name = name
        self.kind = kind
        self.goal = float(goal)
        self.hist = hist
        self.threshold_ms = (
            None if threshold_ms is None else float(threshold_ms)
        )
        self.bad_counters = tuple(bad_counters)
        self.total_counters = tuple(total_counters)


def default_router_slos(ttft_p99_ms, e2e_p99_ms, goodput_goal,
                        latency_goal=0.01):
    """The three objectives the tentpole declares for the routing tier:
    fleet TTFT p99, router e2e p99, and the goodput floor (shed +
    terminal errors over routed)."""
    return [
        SloSpec("ttft_p99", "latency", latency_goal,
                hist="fleet_ttft_ms", threshold_ms=ttft_p99_ms),
        SloSpec("e2e_p99", "latency", latency_goal,
                hist="e2e_ms", threshold_ms=e2e_p99_ms),
        SloSpec("goodput", "availability", goodput_goal,
                bad_counters=("shed", "errors"),
                total_counters=("routed",)),
    ]


class BurnRateEngine(object):
    """Evaluates a set of SloSpecs against one TimeSeriesRing.

    Stateless between calls (the ring IS the state); `evaluate`
    returns plain dict reports so the proto block, the /metrics
    gauges and the autoscaler advisory all read one shape:

        {"name", "kind", "goal", "threshold_ms", "fast_burn",
         "slow_burn", "fast_window_secs", "slow_window_secs",
         "fast_samples", "slow_samples", "alerting"}

    Burns are always FINITE: an empty horizon has bad_fraction 0 (no
    traffic spends no budget), and goal > 0 by construction.
    """

    def __init__(self, specs, fast_window_secs=30.0,
                 slow_window_secs=120.0):
        self.specs = list(specs)
        self.fast_window_secs = float(fast_window_secs)
        self.slow_window_secs = float(slow_window_secs)

    def _bad_total(self, spec, ring, horizon, now):
        if spec.kind == "latency":
            counts = ring.merged_hist_counts(spec.hist, horizon, now)
            total = sum(counts)
            # strictly above the threshold's own bucket: the bucket
            # containing the threshold counts as GOOD (within bucket
            # resolution — the scheme's documented tolerance)
            cut = bucket_index(spec.threshold_ms)
            bad = sum(counts[cut + 1:])
            return bad, total
        bad = sum(ring.sum_counter(c, horizon, now)
                  for c in spec.bad_counters)
        total = sum(ring.sum_counter(c, horizon, now)
                    for c in spec.total_counters)
        return bad, total

    def evaluate(self, ring, now=None):
        reports = []
        for spec in self.specs:
            fb, ft = self._bad_total(
                spec, ring, self.fast_window_secs, now
            )
            sb, st = self._bad_total(
                spec, ring, self.slow_window_secs, now
            )
            fast = (fb / ft / spec.goal) if ft else 0.0
            slow = (sb / st / spec.goal) if st else 0.0
            reports.append({
                "name": spec.name,
                "kind": spec.kind,
                "goal": spec.goal,
                "threshold_ms": spec.threshold_ms or 0.0,
                "fast_burn": fast,
                "slow_burn": slow,
                "fast_window_secs": self.fast_window_secs,
                "slow_window_secs": self.slow_window_secs,
                "fast_samples": ft,
                "slow_samples": st,
                # multi-window rule: both horizons burning above
                # budget — fast alone is a blip, slow alone is history
                "alerting": fast > 1.0 and slow > 1.0,
            })
        return reports
