"""Runtime health plane: recompile sentry, device-memory ledger
reconciliation, and a progress watchdog with a flight recorder.

PRs 6/12/13 built the observability stack from traces up — spans,
histograms, a live /metrics plane, tail forensics — but everything in
it observes REQUESTS. Nothing observes the RUNTIME: the engine's
"churn never recompiles" invariant is asserted by design and measured
nowhere, the paged pool's byte ledger is self-reported and never
reconciled against what the device actually holds, and a wedged
scheduler is detected from the OUTSIDE by lease decay plus a
deliberately conservative 30 s heuristic (serving/autoscaler.py
`wedged_after_secs`). This module makes the runtime self-report, three
layers behind one `ServingConfig.runtime_health` switch:

* **RecompileSentry** — every `jax.jit` call site in the serving
  engine, the paged KV pool and the offline decode paths is adopted
  through `tracked_jit`, which counts COMPILATIONS per named
  executable (the wrapped python fn runs exactly once per trace, i.e.
  per compile-cache miss — the lowering-hook variant of
  `_cache_size()` probing, with no jax-version coupling). First
  compiles of a name are the cold path by design (one executable per
  prefill/suffix bucket); a SECOND compile of the same name is a
  RECOMPILE, and after `mark_steady()` (the post-warmup boundary) a
  recompile is a counted, trace-evented ANOMALY — the invariant
  serve-smoke asserts at zero. Exposed as the closed labeled family
  `edl_serving_recompiles_total{fn=...}`.

* **DeviceMemoryAccountant** — periodic reconciliation of the
  runtime's own ledger (pool `bytes_total` + host-tier bytes + param
  bytes + draft-pool bytes) against JAX's live-buffer view
  (`jax.live_arrays()` byte sum, plus `device.memory_stats()` where
  the backend provides it). Drift since the baseline —
  device bytes the ledger cannot name — lands in the
  `memory_unaccounted_bytes` gauge with a monotone peak watermark, so
  a leaked donated buffer or an executable cache growing without
  bound is VISIBLE before it is fatal. The `health_leak` fault hook
  leaks a buffer on purpose so the drill can prove the accountant
  convicts it.

* **ProgressWatchdog + FlightRecorder** — a bounded ring of per-tick
  engine snapshots (seated slots, queue depth, blocks
  free/cached/host, tokens committed, step ms) fed by the scheduler,
  and a watchdog that runs on its OWN thread (the whole point: the
  scheduler being wedged is the failure under observation) and
  declares `stalled` only when work is seated/queued but the progress
  counter — tokens committed PLUS jit compiles, so a long cold
  compile is progress, not a stall — has not moved for
  `stall_after_secs`. Idle is healthy. On the transition to stalled
  it atomically dumps a DIAGNOSTIC BUNDLE to `$EDL_HEALTH_DIR`:
  all-thread stacks (faulthandler), the snapshot ring, the two-tier
  pool ledger, the reconciliation view and the recompile counters —
  the flight recorder of the crash. `last_progress_age_ms` +
  `health_state` ride ServerStatus/ReplicaStatus so the autoscaler
  can replace a self-reported stalled replica in seconds instead of
  the 30 s lease heuristic (scripts/run_stall_drill.py proves the
  latency gap).

Thread model: the scheduler thread feeds (`record_tick`, and compiles
happen on it), the health thread checks/reconciles, gRPC status
threads read snapshots — every structure carries its own lock, and no
health lock is ever held while taking the telemetry lock's callbacks
(the mirror pattern: read under own lock, count deltas outside).

`install_sigusr2_dump()` is the standalone escape hatch every
long-running entrypoint registers: SIGUSR2 -> faulthandler all-thread
stack dump to stderr (or `$EDL_HEALTH_DIR/sigusr2-<pid>.txt`), so a
live wedged process can always be interrogated without killing it.

Design doc: docs/designs/observability.md ("Runtime health").
"""

import faulthandler
import io
import json
import os
import signal
import threading
import time
import traceback
from collections import deque

from elasticdl_tpu.common.log_utils import default_logger as logger

HEALTH_DIR_ENV = "EDL_HEALTH_DIR"
HEALTH_ENV = "EDL_RUNTIME_HEALTH"
STALL_AFTER_ENV = "EDL_STALL_AFTER_SECS"

#: the closed health-state set (ServerStatus.health_state); "" on the
#: wire means the replica predates the health plane (or runs with it
#: off) — the autoscaler's cue to fall back to lease decay
HEALTH_STATES = ("ok", "stalled")

BUNDLE_SCHEMA = "edl-health-bundle/1"


def runtime_health_default():
    """EDL_RUNTIME_HEALTH resolves the health plane when the config
    leaves it unset: on unless explicitly '0' (the plane's cost is
    bounded by the serve-smoke overhead A/B, like forensics)."""
    return os.environ.get(HEALTH_ENV, "1") != "0"


def stall_after_default():
    """EDL_STALL_AFTER_SECS resolves the watchdog budget when the
    config leaves it unset (default 10 s: far above any healthy step,
    far below the 30 s lease heuristic it exists to beat)."""
    try:
        return float(os.environ.get(STALL_AFTER_ENV, "") or 10.0)
    except ValueError:
        return 10.0


def health_dir_default():
    """$EDL_HEALTH_DIR, or "" = bundles off (stalls still count and
    advertise; only the on-disk dump is skipped)."""
    return os.environ.get(HEALTH_DIR_ENV, "")


# ------------------------------------------------------ recompile sentry


class RecompileSentry(object):
    """Per-named-executable compilation counts, with a steady-state
    boundary. `record_compile` is called from inside the traced
    function (tracked_jit), i.e. on whatever thread triggered the
    compile; reads come from the health/status threads — one lock.

    Vocabulary: a COMPILE is any cache-miss trace of a tracked jit; a
    RECOMPILE is a compile of a name that was already compiled once
    (the engine's call sites all carry fixed shapes per name, so a
    recompile is never legitimate); a STEADY RECOMPILE is a recompile
    after `mark_steady()` — the anomaly class serve-smoke pins at
    zero. First compiles of a NEW name after the boundary are fine:
    a prefill bucket first exercised mid-serve is the cold path
    working as designed, not churn recompiling."""

    #: anomaly ring bound (each entry is tiny; 256 outlives any drill)
    MAX_ANOMALIES = 256

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.compiles = {}  # fn name -> compile count
        self.recompiles = 0
        self.steady_recompiles = 0
        self.steady_at = None
        self.anomalies = deque(maxlen=self.MAX_ANOMALIES)

    def record_compile(self, name):
        anomaly = False
        with self._lock:
            n = self.compiles.get(name, 0) + 1
            self.compiles[name] = n
            if n > 1:
                self.recompiles += 1
                if self.steady_at is not None:
                    self.steady_recompiles += 1
                    self.anomalies.append(
                        {"fn": name, "count": n, "at": self._clock()}
                    )
                    anomaly = True
        if anomaly:
            # trace-evented: the anomaly is a causal node operators
            # can see next to the requests it slowed (best-effort —
            # the sentry must never make a compile fail)
            try:
                from elasticdl_tpu.observability.tracing import (
                    recorder,
                )

                recorder().start_span(
                    "recompile_anomaly", fn=name, compile_count=n,
                ).finish("anomaly")
            except Exception:  # pragma: no cover - never block
                pass
            logger.warning(
                "runtime health: STEADY-STATE RECOMPILE of %r "
                "(compile #%d) — the zero-recompile invariant is "
                "broken", name, n,
            )

    def mark_steady(self):
        """Declare the warmup over: from here on a recompile is an
        anomaly, not a cold start. Idempotent (the first mark wins, so
        a second warmup pass cannot move the boundary forward past
        real anomalies)."""
        with self._lock:
            if self.steady_at is None:
                self.steady_at = self._clock()

    def total_compiles(self):
        with self._lock:
            return sum(self.compiles.values())

    def snapshot(self):
        with self._lock:
            return {
                "compiles": dict(self.compiles),
                "total_compiles": sum(self.compiles.values()),
                "recompiles": self.recompiles,
                "steady_recompiles": self.steady_recompiles,
                "steady_marked": self.steady_at is not None,
                "anomalies": list(self.anomalies),
            }

    def prometheus(self):
        """The closed labeled family: one `fn` label per tracked
        executable name that compiled at least once."""
        from elasticdl_tpu.observability.metrics import (
            labeled_counter_family,
        )

        with self._lock:
            series = [({"fn": name}, n)
                      for name, n in sorted(self.compiles.items())]
        return [labeled_counter_family(
            "edl_serving_recompiles_total",
            "jit compilations per named executable (recompile sentry; "
            "count > 1 for any fn = the zero-recompile invariant is "
            "broken)",
            series,
        )]


def tracked_jit(fn, name, sentry, **jit_kwargs):
    """`jax.jit(fn)` with compilation counting: the wrapped python
    function body runs exactly once per compile-cache miss (trace =
    compile for pjit), so a trace-time callback IS the compile
    counter — no dependence on private jit internals. `sentry` may be
    a RecompileSentry, None (counting off, still jitted), or a
    zero-arg callable resolving to either at trace time — the lazy
    form lets an engine wrap executables in __init__ and attach the
    sentry afterwards without losing later compiles."""
    import functools

    import jax

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        s = sentry() if callable(sentry) else sentry
        if s is not None:
            s.record_compile(name)
        return fn(*args, **kwargs)

    # wraps() keeps fn's inspectable signature, so jit options that
    # resolve parameter NAMES (static_argnames) still bind correctly
    return jax.jit(traced, **jit_kwargs)


# -------------------------------------------------- memory accountant


def _jax_live_bytes():
    """JAX's view of resident array bytes in this process, plus the
    backend allocator's own stats where the platform provides them
    (TPU/GPU `memory_stats`; CPU returns None)."""
    import jax

    live = 0
    for arr in jax.live_arrays():
        try:
            live += int(arr.nbytes)
        except Exception:  # noqa: BLE001 - a deleted array mid-walk
            continue
    stats = None
    try:
        raw = jax.devices()[0].memory_stats()
        if raw:
            stats = {k: int(v) for k, v in raw.items()
                     if isinstance(v, (int, float))}
    except Exception:  # noqa: BLE001 - CPU backends: no stats
        stats = None
    return live, stats


class DeviceMemoryAccountant(object):
    """Reconciles the runtime's self-reported byte ledger against the
    device's actual holdings.

    Ledger side (what the runtime can NAME): the KV pool's
    `kv_bytes_total` + host-tier bytes + param bytes (the served
    float tree AND the int8 source when they differ) + the draft
    pool. Device side: `jax.live_arrays()` byte sum. The difference
    can never be zero — executables pin constants, prefill buffers
    come and go — so the accountant BASELINES at `rebase()` (the
    steady boundary) and reports DRIFT since then:

        unaccounted = max(0, (live - ledger) - baseline)

    A healthy steady-state serve oscillates near zero; a leaked
    buffer (or an executable cache growing per-request) climbs and
    never comes back — which is what the monotone peak watermark
    `memory_unaccounted_peak_bytes` records. `live_bytes_fn` is
    injectable for tests."""

    def __init__(self, engine, live_bytes_fn=None):
        self._engine = engine
        self._live_bytes = live_bytes_fn or _jax_live_bytes
        self._lock = threading.Lock()
        self._baseline = None
        self.unaccounted_bytes = 0
        self.unaccounted_peak_bytes = 0
        self.reconciles = 0
        self.last = {}
        # the drill's deliberate leak: buffers held here are device-
        # resident and absent from every ledger line by construction
        self._leaked = []

    def _param_bytes(self):
        import jax

        seen = set()
        total = 0
        for attr in ("_exec_variables", "variables", "_d_variables"):
            tree = getattr(self._engine, attr, None)
            if tree is None:
                continue
            for leaf in jax.tree.leaves(tree):
                nbytes = getattr(leaf, "nbytes", None)
                if nbytes is None:
                    continue
                key = id(leaf)
                if key in seen:
                    continue  # non-quantized: exec IS variables
                seen.add(key)
                total += int(nbytes)
        return total

    def _draft_pool_bytes(self):
        import jax

        pool = getattr(self._engine, "_d_pool", None)
        if pool is None:
            return 0
        return sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree.leaves(pool))

    def ledger(self):
        """The bytes the runtime can account for, by line item."""
        kv = self._engine.kv_stats()
        return {
            "kv_bytes_total": int(kv.get("kv_bytes_total", 0)),
            "kv_host_bytes": int(kv.get("kv_host_bytes", 0)),
            "param_bytes": self._param_bytes(),
            "draft_pool_bytes": self._draft_pool_bytes(),
        }

    def reconcile(self, now=None):
        """One reconciliation pass (health thread cadence). Returns
        the current view dict; updates the drift gauge + peak."""
        ledger = self.ledger()
        ledger_total = sum(ledger.values())
        live, device_stats = self._live_bytes()
        raw_gap = live - ledger_total
        with self._lock:
            if self._baseline is None:
                self._baseline = raw_gap
            unaccounted = max(0, raw_gap - self._baseline)
            self.unaccounted_bytes = unaccounted
            self.unaccounted_peak_bytes = max(
                self.unaccounted_peak_bytes, unaccounted
            )
            self.reconciles += 1
            self.last = {
                "ledger": ledger,
                "ledger_bytes": ledger_total,
                "live_bytes": live,
                "baseline_gap_bytes": self._baseline,
                "unaccounted_bytes": unaccounted,
                "unaccounted_peak_bytes": self.unaccounted_peak_bytes,
                "device_stats": device_stats,
            }
            return dict(self.last)

    def rebase(self):
        """Re-baseline the drift at the CURRENT gap — the steady
        boundary calls this so warmup's executable constants never
        masquerade as a leak. The peak resets too: pre-steady drift
        is definitionally not a leak, and the watermark must answer
        'has it drifted SINCE steady'."""
        ledger_total = sum(self.ledger().values())
        live, _ = self._live_bytes()
        with self._lock:
            self._baseline = live - ledger_total
            self.unaccounted_bytes = 0
            self.unaccounted_peak_bytes = 0

    def leak_for_drill(self, nbytes):
        """Allocate and HOLD a device buffer the ledger cannot name —
        the fault-injection payload that proves reconciliation
        convicts a real leak (never called outside the health_leak
        hook)."""
        import jax.numpy as jnp

        buf = jnp.zeros((max(1, int(nbytes)),), jnp.int8)
        buf.block_until_ready()
        with self._lock:
            self._leaked.append(buf)
        logger.warning(
            "runtime health: health_leak fault leaked %d device "
            "bytes on purpose", buf.nbytes,
        )
        return int(buf.nbytes)

    def snapshot(self):
        with self._lock:
            return {
                "unaccounted_bytes": self.unaccounted_bytes,
                "unaccounted_peak_bytes": self.unaccounted_peak_bytes,
                "reconciles": self.reconciles,
                "leaked_buffers": len(self._leaked),
                "last": dict(self.last),
            }


# ---------------------------------------------- watchdog + flight ring


class FlightRecorder(object):
    """Bounded ring of per-tick engine snapshots — the drop-OLDEST +
    monotone `dropped` contract every ring in the system keeps. The
    scheduler records; the bundle dump and status threads snapshot."""

    def __init__(self, capacity=256):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self.recorded = 0
        self.dropped = 0

    def record(self, snap):
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(dict(snap))
            self.recorded += 1

    def snapshot(self):
        with self._lock:
            return [dict(s) for s in self._ring]


class ProgressWatchdog(object):
    """Stall = work is seated (or queued) but the progress counter has
    not moved for `stall_after_secs`. Idle (no work anywhere) is
    healthy by definition, and the caller folds jit compiles into the
    progress counter so a cold compile can never read as a stall.
    `observe()` returns True exactly on the ok->stalled transition
    (the bundle-dump edge); recovery (tokens flow again) returns to
    "ok" silently."""

    def __init__(self, stall_after_secs=10.0, clock=time.monotonic):
        self.stall_after_secs = float(stall_after_secs)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "ok"
        self.stalls = 0
        self._last_progress_at = clock()
        self._last_counter = None
        self._last_work = 0

    def observe(self, work, progress_counter, now=None):
        now = self._clock() if now is None else now
        with self._lock:
            if (self._last_counter is None
                    or progress_counter != self._last_counter
                    or not work):
                self._last_progress_at = now
            self._last_counter = progress_counter
            self._last_work = work
            age = now - self._last_progress_at
            stalled = bool(work) and age >= self.stall_after_secs
            transition = stalled and self.state != "stalled"
            self.state = "stalled" if stalled else "ok"
            if transition:
                self.stalls += 1
            return transition

    def last_progress_age_ms(self, now=None):
        """Ms since progress last moved WITH work present; an idle
        watchdog reads 0 (the wire contract: 0 = idle or moving)."""
        now = self._clock() if now is None else now
        with self._lock:
            if not self._last_work:
                return 0.0
            return max(0.0, (now - self._last_progress_at) * 1000.0)

    def snapshot(self, now=None):
        now = self._clock() if now is None else now
        with self._lock:
            age = (
                max(0.0, (now - self._last_progress_at) * 1000.0)
                if self._last_work else 0.0
            )
            return {
                "state": self.state,
                "stalls": self.stalls,
                "last_progress_age_ms": age,
                "stall_after_secs": self.stall_after_secs,
            }


# ------------------------------------------------------- bundle writer


def _all_thread_stacks():
    """All-thread stacks, twice over: faulthandler's raw dump (the
    signal-safe ground truth — it shows frames even for threads the
    interpreter-level walk cannot name) plus a python-level walk with
    thread NAMES, which is what makes the bundle readable."""
    fh = ""
    try:
        buf = io.StringIO()
        faulthandler.dump_traceback(file=buf, all_threads=True)
        fh = buf.getvalue()
    except Exception:  # noqa: BLE001 - some files reject dump
        fh = ""
    names = {t.ident: t.name for t in threading.enumerate()}
    frames = {}
    try:
        import sys

        frames = sys._current_frames()
    except Exception:  # noqa: BLE001 - best effort
        frames = {}
    threads = []
    for ident, frame in frames.items():
        threads.append({
            "thread": names.get(ident, "ident-%s" % ident),
            "stack": traceback.format_stack(frame),
        })
    return {"faulthandler": fh, "threads": threads}


#: required bundle keys -> required type (the drill's schema gate)
_BUNDLE_SCHEMA_KEYS = {
    "schema": str,
    "reason": str,
    "pid": int,
    "unix_ts": float,
    "health": dict,
    "ring": list,
    "kv_ledger": dict,
    "memory": dict,
    "recompiles": dict,
    "stacks": dict,
}


def validate_bundle(bundle):
    """Schema-gate a diagnostic bundle dict; returns a list of
    problems ([] = valid). The drill and the unit tests call this so
    'a bundle was written' always means 'a bundle a human can read'."""
    problems = []
    if not isinstance(bundle, dict):
        return ["bundle is not a dict"]
    for key, typ in _BUNDLE_SCHEMA_KEYS.items():
        if key not in bundle:
            problems.append("missing key %r" % key)
        elif not isinstance(bundle[key], typ):
            problems.append(
                "key %r: expected %s, got %s"
                % (key, typ.__name__, type(bundle[key]).__name__)
            )
    if bundle.get("schema") != BUNDLE_SCHEMA:
        problems.append("schema %r != %r"
                        % (bundle.get("schema"), BUNDLE_SCHEMA))
    stacks = bundle.get("stacks")
    if isinstance(stacks, dict) and not (
            stacks.get("faulthandler") or stacks.get("threads")):
        problems.append("stacks carry neither faulthandler text nor "
                        "a thread walk")
    return problems


def write_bundle(health_dir, bundle):
    """Atomic (tmp+rename) JSON dump — the span-export contract: a
    reader never sees a torn bundle. Returns the final path."""
    os.makedirs(health_dir, exist_ok=True)
    name = "health-bundle-%d-%d.json" % (
        bundle.get("pid", os.getpid()), bundle.get("seq", 0),
    )
    path = os.path.join(health_dir, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bundle, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


# --------------------------------------------------------- the owner


class RuntimeHealth(object):
    """The health plane's owner: one sentry + accountant + watchdog +
    flight ring, and the daemon thread that drives checks/reconciles
    INDEPENDENTLY of the scheduler (whose failure is the thing under
    observation).

    Wiring: GenerationServer constructs it when
    `ServingConfig.runtime_health` is on, attaches `self.sentry` to
    the engine (which forwards it to the KV pool and the offline
    decode caches), hands `record_tick` to the scheduler loop, and
    reads `snapshot()` for ServerStatus. The telemetry mirror follows
    the PR 11 pattern (the pool's `_sync_host_telemetry`): the sentry
    and watchdog are the single source of truth; the closed telemetry
    counters/gauges receive DELTAS so the scrape plane can never
    drift from them."""

    def __init__(self, engine, queue, telemetry,
                 stall_after_secs=None, check_secs=0.25,
                 reconcile_secs=2.0, ring_capacity=256,
                 health_dir=None, injector=None,
                 clock=time.monotonic, live_bytes_fn=None):
        self._engine = engine
        self._queue = queue
        self._telemetry = telemetry
        self._clock = clock
        self.check_secs = float(check_secs)
        self.reconcile_secs = float(reconcile_secs)
        self.health_dir = (
            health_dir_default() if health_dir is None else health_dir
        )
        self._injector = injector
        self.sentry = RecompileSentry(clock=clock)
        self.accountant = DeviceMemoryAccountant(
            engine, live_bytes_fn=live_bytes_fn
        )
        self.watchdog = ProgressWatchdog(
            stall_after_default() if stall_after_secs is None
            else stall_after_secs,
            clock=clock,
        )
        self.recorder = FlightRecorder(capacity=ring_capacity)
        self.bundles = []  # paths written (drill/status introspection)
        self._bundle_seq = 0
        self._leak_checked = False
        self._steady_seen = 0  # steady_recompiles mirrored so far
        self._stalls_seen = 0
        self._last_reconcile = 0.0
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------ lifecycle

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="runtime-health"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                now = self._clock()
                self.check(now)
                if now - self._last_reconcile >= self.reconcile_secs:
                    self.reconcile(now)
                    self._last_reconcile = now
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("runtime health tick failed")
            self._stop.wait(self.check_secs)

    # ------------------------------------------------------- feeding

    def mark_steady(self):
        """The post-warmup boundary: recompiles become anomalies and
        the memory baseline re-anchors past the warmup's executable
        constants."""
        self.sentry.mark_steady()
        try:
            self.accountant.rebase()
        except Exception:  # noqa: BLE001 - a bare test engine
            logger.exception("runtime health: rebase failed")

    def record_tick(self, queue_depth, active_slots, step_secs,
                    tokens_committed):
        """One scheduler tick into the flight ring (scheduler thread).
        KV occupancy is read engine-side so the ring shows the pool
        the way the stalled step last saw it."""
        try:
            kv = self._engine.kv_stats()
        except Exception:  # noqa: BLE001 - mid-teardown
            kv = {}
        self.recorder.record({
            "t": self._clock(),
            "queue_depth": int(queue_depth),
            "active_slots": int(active_slots),
            "step_ms": round(float(step_secs) * 1000.0, 3),
            "tokens_committed": int(tokens_committed),
            "kv_blocks_free": kv.get("kv_blocks_free", 0),
            "kv_blocks_cached": kv.get("kv_blocks_cached", 0),
            "kv_host_blocks": kv.get("kv_host_blocks", 0),
            "kv_bytes_in_use": kv.get("kv_bytes_in_use", 0),
        })

    # ------------------------------------------------------- checking

    def _progress_counter(self):
        """Tokens committed + compiles finished: either moving means
        the scheduler is ALIVE. The counter dict read is a GIL-atomic
        int fetch — deliberately lock-free (a stale read delays
        detection by one check period, never fabricates a stall)."""
        tokens = self._telemetry.counters.get("tokens_generated", 0)
        return tokens + self.sentry.total_compiles()

    def _work_present(self):
        try:
            seated = self._engine.active_count()
        except Exception:  # noqa: BLE001 - mid-teardown
            seated = 0
        try:
            queued = len(self._queue)
        except Exception:  # noqa: BLE001
            queued = 0
        return seated + queued

    def check(self, now=None):
        """One watchdog evaluation (health thread, or any thread —
        the drill's status reads converge on the same state). On the
        ok->stalled transition: count the stall, dump the bundle."""
        now = self._clock() if now is None else now
        transition = self.watchdog.observe(
            self._work_present(), self._progress_counter(), now=now
        )
        if transition:
            self._telemetry.count("stalls")
            try:
                from elasticdl_tpu.observability.tracing import (
                    recorder,
                )

                recorder().start_span(
                    "progress_stall",
                    age_ms=self.watchdog.last_progress_age_ms(now),
                ).finish("stalled")
            except Exception:  # pragma: no cover - best effort
                pass
            self.dump_bundle("progress_stall")
        return transition

    def reconcile(self, now=None):
        """One ledger reconciliation + telemetry mirror pass (health
        thread cadence). The health_leak fault hook fires here — the
        drill's deliberate leak happens exactly once per armed rule,
        then the next reconcile convicts it."""
        self._maybe_leak()
        try:
            self.accountant.reconcile(now)
        except Exception:  # noqa: BLE001 - bare test engines
            logger.exception("runtime health: reconcile failed")
        snap = self.accountant.snapshot()
        self._telemetry.gauge("memory_unaccounted_bytes",
                              snap["unaccounted_peak_bytes"])
        self._telemetry.gauge(
            "last_progress_age_ms",
            self.watchdog.last_progress_age_ms(now),
        )
        # mirror the sentry's anomaly count by delta (single source
        # of truth stays the sentry)
        steady = self.sentry.snapshot()["steady_recompiles"]
        if steady > self._steady_seen:
            self._telemetry.count("steady_recompiles",
                                  steady - self._steady_seen)
            self._steady_seen = steady

    def _maybe_leak(self):
        # the drill's leak tests STEADY-STATE reconciliation: firing
        # before the warmup boundary would be absorbed by the rebase
        if (self._injector is None
                or not self.sentry.snapshot()["steady_marked"]):
            return
        try:
            self._injector.intercept("health_leak")
        except Exception:  # noqa: BLE001 - the armed rule fired
            self.accountant.leak_for_drill(8 << 20)

    # ------------------------------------------------------- reading

    def health_state(self, now=None):
        return self.watchdog.state

    def snapshot(self, now=None):
        now = self._clock() if now is None else now
        wd = self.watchdog.snapshot(now)
        sentry = self.sentry.snapshot()
        mem = self.accountant.snapshot()
        return {
            "health_state": wd["state"],
            "last_progress_age_ms": wd["last_progress_age_ms"],
            "stalls": wd["stalls"],
            "jit_compiles": sentry["total_compiles"],
            "recompiles": sentry["recompiles"],
            "steady_recompiles": sentry["steady_recompiles"],
            "steady_marked": sentry["steady_marked"],
            "memory_unaccounted_bytes": mem["unaccounted_peak_bytes"],
            "bundles": list(self.bundles),
            "ring_recorded": self.recorder.recorded,
        }

    def prometheus(self):
        """Exposition families only the health plane can render: the
        per-fn recompile family. (The scalar gauges/counters ride the
        closed telemetry sets via the mirror.)"""
        return self.sentry.prometheus()

    # --------------------------------------------------------- bundle

    def dump_bundle(self, reason, now=None):
        """Atomically dump the diagnostic bundle; returns the path or
        None (no EDL_HEALTH_DIR = advertise-only mode)."""
        now = self._clock() if now is None else now
        if not self.health_dir:
            return None
        try:
            kv = self._engine.kv_stats()
        except Exception:  # noqa: BLE001
            kv = {}
        self._bundle_seq += 1
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "pid": os.getpid(),
            "seq": self._bundle_seq,
            "unix_ts": time.time(),
            "health": self.watchdog.snapshot(now),
            "ring": self.recorder.snapshot(),
            "ring_dropped": self.recorder.dropped,
            "kv_ledger": kv,
            "memory": self.accountant.snapshot(),
            "recompiles": self.sentry.snapshot(),
            "stacks": _all_thread_stacks(),
        }
        try:
            path = write_bundle(self.health_dir, bundle)
        except OSError:
            logger.exception("runtime health: bundle dump failed")
            return None
        self.bundles.append(path)
        logger.warning("runtime health: %s bundle dumped to %s",
                       reason, path)
        return path


# ------------------------------------------------------------- SIGUSR2


def install_sigusr2_dump(to_health_dir=True):
    """Register SIGUSR2 -> faulthandler all-thread stack dump, so a
    live wedged process can always be interrogated without killing
    it:

        kill -USR2 <pid>

    With $EDL_HEALTH_DIR set (and to_health_dir), stacks append to
    `sigusr2-<pid>.txt` there — interrogation survives a rotated or
    discarded stderr; otherwise they go to stderr. Returns the dump
    file path ("" = stderr). Idempotent and best-effort: entrypoints
    call it unconditionally, and a platform without SIGUSR2 or
    faulthandler registration (threads, exotic runtimes) is a no-op,
    never a crash."""
    try:
        target = ""
        stream = None
        if to_health_dir and health_dir_default():
            os.makedirs(health_dir_default(), exist_ok=True)
            target = os.path.join(
                health_dir_default(), "sigusr2-%d.txt" % os.getpid()
            )
            stream = open(target, "a")  # noqa: SIM115 - lives forever
        faulthandler.register(
            signal.SIGUSR2, all_threads=True, chain=False,
            **({"file": stream} if stream is not None else {}),
        )
        logger.info(
            "SIGUSR2 stack dump armed (-> %s)", target or "stderr"
        )
        return target
    except (AttributeError, ValueError, OSError):
        # no SIGUSR2 (platform) / not the main thread / bad dir
        logger.warning("SIGUSR2 stack dump could not be registered")
        return None
