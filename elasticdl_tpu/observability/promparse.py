"""Independent Prometheus text-format parser (verification only).

This module deliberately shares NOTHING with the renderer in
observability/metrics.py — no helper, no constant, no regex — so the
drill and the metrics-plane tests can round-trip an exposition through
an implementation that could not have inherited the renderer's bugs
(the same independence contract as the bitwise-CRC32C tb_events parser
in tests/test_observability.py). It parses text format 0.0.4 line by
line and VALIDATES structure as it goes:

* every sample belongs to a family announced by `# TYPE` (histogram
  samples may only use the `_bucket`/`_sum`/`_count` suffixes, counter
  samples must end in `_total`);
* metric and label names match the Prometheus grammar;
* label values un-escape `\\\\`, `\\"`, `\\n`;
* sample values parse as floats (`+Inf`/`-Inf`/`NaN` included);
* per histogram series (same non-`le` labels): `_bucket` cumulative
  counts are monotone in `le`, a `+Inf` bucket exists, and `_count`
  equals it;
* OpenMetrics exemplars (``... # {trace_id="..."} 12.3 1722800000``)
  are accepted ONLY on histogram `_bucket` samples, must carry at
  least one well-formed label, a finite value, and — on a finite-`le`
  bucket — a value not above that bucket's upper bound (an exemplar
  must be a sample the bucket could actually have counted). Parsed
  exemplars land in each family's ``exemplars`` list as
  ``(metric_name, labels, exemplar_labels, value, unix_ts_or_None)``.

Raises ValueError on ANY violation — a parse is a pass/fail check, not
a best-effort scrape.
"""

import math

_NAME_FIRST = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_:"
_NAME_REST = _NAME_FIRST + "0123456789"


def _valid_name(name):
    return (bool(name) and name[0] in _NAME_FIRST
            and all(ch in _NAME_REST for ch in name))


def _parse_float(text):
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def _parse_labels(text, lineno):
    """`key="value",...` (inside braces) -> dict, honoring escapes."""
    labels = {}
    i = 0
    while i < len(text):
        j = text.index("=", i)
        key = text[i:j]
        if not _valid_name(key) or ":" in key:
            raise ValueError(
                "line %d: bad label name %r" % (lineno, key)
            )
        if j + 1 >= len(text) or text[j + 1] != '"':
            raise ValueError(
                "line %d: unquoted label value" % lineno
            )
        i = j + 2
        out = []
        while True:
            if i >= len(text):
                raise ValueError(
                    "line %d: unterminated label value" % lineno
                )
            ch = text[i]
            if ch == "\\":
                nxt = text[i + 1:i + 2]
                if nxt == "n":
                    out.append("\n")
                elif nxt in ('"', "\\"):
                    out.append(nxt)
                else:
                    raise ValueError(
                        "line %d: bad escape \\%s" % (lineno, nxt)
                    )
                i += 2
                continue
            if ch == '"':
                i += 1
                break
            out.append(ch)
            i += 1
        labels[key] = "".join(out)
        if i < len(text):
            if text[i] != ",":
                raise ValueError(
                    "line %d: junk after label value: %r"
                    % (lineno, text[i:])
                )
            i += 1
    return labels


def _split_exemplar(line):
    """Split a SAMPLE line at its exemplar separator — the first `#`
    outside quoted label values — returning (main, exemplar_text or
    None). A `#` inside a quoted label value never splits."""
    in_quotes = False
    i = 0
    while i < len(line):
        ch = line[i]
        if ch == "\\" and in_quotes:
            i += 2
            continue
        if ch == '"':
            in_quotes = not in_quotes
        elif ch == "#" and not in_quotes:
            return line[:i].rstrip(), line[i + 1:].strip()
        i += 1
    return line, None


def _parse_exemplar(text, lineno):
    """`{labels} value [unix_ts]` -> (labels, value, ts_or_None)."""
    if not text.startswith("{"):
        raise ValueError(
            "line %d: exemplar must start with a label set, got %r"
            % (lineno, text)
        )
    close = text.find("}")
    if close < 0:
        raise ValueError(
            "line %d: unterminated exemplar label set" % lineno
        )
    labels = _parse_labels(text[1:close], lineno)
    if not labels:
        raise ValueError(
            "line %d: exemplar has no labels" % lineno
        )
    rest = text[close + 1:].split()
    if not rest or len(rest) > 2:
        raise ValueError(
            "line %d: exemplar needs `value [timestamp]`, got %r"
            % (lineno, text[close + 1:])
        )
    value = _parse_float(rest[0])
    if not (value == value and abs(value) != math.inf):
        raise ValueError(
            "line %d: exemplar value %r is not finite"
            % (lineno, rest[0])
        )
    ts = _parse_float(rest[1]) if len(rest) == 2 else None
    return labels, value, ts


def parse_prometheus_text(text):
    """Parse + validate one exposition. Returns
    {family: {"type": ..., "help": ..., "samples":
    [(metric_name, labels_dict, value)], "exemplars":
    [(metric_name, labels_dict, exemplar_labels, value, ts)]}}."""
    families = {}
    current = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _valid_name(name):
                raise ValueError(
                    "line %d: bad family name %r" % (lineno, name)
                )
            families.setdefault(
                name, {"type": None, "help": None, "samples": [],
                       "exemplars": []}
            )["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2:
                raise ValueError("line %d: bad TYPE line" % lineno)
            name, mtype = parts
            if mtype not in ("counter", "gauge", "histogram",
                            "summary", "untyped"):
                raise ValueError(
                    "line %d: unknown type %r" % (lineno, mtype)
                )
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": [],
                       "exemplars": []}
            )
            fam["type"] = mtype
            current = name
            continue
        if line.startswith("#"):
            continue  # comment
        # sample line: name[{labels}] value [ts] [# exemplar]
        line, exemplar_text = _split_exemplar(line)
        brace = line.find("{")
        if brace >= 0:
            name = line[:brace]
            close = line.rindex("}")
            labels = _parse_labels(line[brace + 1:close], lineno)
            rest = line[close + 1:].split()
        else:
            parts = line.split()
            name, rest = parts[0], parts[1:]
            labels = {}
        if not _valid_name(name):
            raise ValueError(
                "line %d: bad metric name %r" % (lineno, name)
            )
        if not rest:
            raise ValueError("line %d: sample has no value" % lineno)
        value = _parse_float(rest[0])
        fam = _owning_family(families, name, current, lineno)
        families[fam]["samples"].append((name, labels, value))
        if exemplar_text is not None:
            if (families[fam]["type"] != "histogram"
                    or not name.endswith("_bucket")):
                raise ValueError(
                    "line %d: exemplar on %r — exemplars are only "
                    "valid on histogram _bucket samples"
                    % (lineno, name)
                )
            ex_labels, ex_value, ex_ts = _parse_exemplar(
                exemplar_text, lineno
            )
            le = _parse_float(labels.get("le", "+Inf"))
            if not math.isinf(le) and ex_value > le:
                raise ValueError(
                    "line %d: exemplar value %r above the bucket "
                    "bound le=%r — the bucket could never have "
                    "counted it" % (lineno, ex_value, le)
                )
            families[fam]["exemplars"].append(
                (name, labels, ex_labels, ex_value, ex_ts)
            )
    _validate(families)
    return families


def _owning_family(families, name, current, lineno):
    if name in families:
        return name
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name[:-len(suffix)] in families:
            base = name[:-len(suffix)]
            if families[base]["type"] not in ("histogram", "summary"):
                raise ValueError(
                    "line %d: %r uses a histogram suffix but %r is a "
                    "%s" % (lineno, name, base, families[base]["type"])
                )
            return base
    if current is not None and name == current:
        return current
    raise ValueError(
        "line %d: sample %r belongs to no announced family"
        % (lineno, name)
    )


def _series_key(labels):
    return tuple(sorted(
        (k, v) for k, v in labels.items() if k != "le"
    ))


def _validate(families):
    for fam, info in families.items():
        if info["type"] is None:
            raise ValueError("family %r has samples but no TYPE" % fam)
        if info["type"] == "counter":
            for name, _labels, value in info["samples"]:
                if not name.endswith("_total"):
                    raise ValueError(
                        "counter sample %r does not end in _total"
                        % name
                    )
                if not (value >= 0 or math.isnan(value)):
                    raise ValueError(
                        "counter %r is negative: %r" % (name, value)
                    )
        if info["type"] != "histogram":
            continue
        buckets = {}
        counts = {}
        for name, labels, value in info["samples"]:
            key = _series_key(labels)
            if name == fam + "_bucket":
                if "le" not in labels:
                    raise ValueError(
                        "histogram %r bucket without le" % fam
                    )
                buckets.setdefault(key, []).append(
                    (_parse_float(labels["le"]), value)
                )
            elif name == fam + "_count":
                counts[key] = value
        for key, series in buckets.items():
            series.sort(key=lambda p: p[0])
            if not series or not math.isinf(series[-1][0]):
                raise ValueError(
                    "histogram %r series %r lacks a +Inf bucket"
                    % (fam, key)
                )
            last = -1.0
            for le, cum in series:
                if cum < last:
                    raise ValueError(
                        "histogram %r series %r buckets are not "
                        "monotone at le=%r" % (fam, key, le)
                    )
                last = cum
            if key in counts and counts[key] != series[-1][1]:
                raise ValueError(
                    "histogram %r series %r: _count %r != +Inf "
                    "bucket %r"
                    % (fam, key, counts[key], series[-1][1])
                )
