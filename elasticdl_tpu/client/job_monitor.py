"""Client-side job monitoring: poll pod phases, tail master logs
(reference common/k8s_job_monitor.py: PodMonitor / EdlJobMonitor,
213 LoC). Works against any object with the CoreV1Api read/log surface,
so tests drive it with fakes."""

import time

from elasticdl_tpu.common.log_utils import default_logger as logger

_FINISHED_PHASES = ("Succeeded", "Failed")


def _phase(pod):
    if pod is None:
        return None
    status = (
        pod.get("status") if isinstance(pod, dict)
        else getattr(pod, "status", None)
    )
    if status is None:
        return None
    return (
        status.get("phase") if isinstance(status, dict)
        else getattr(status, "phase", None)
    )


class PodMonitor(object):
    """Poll one pod until it reaches a terminal phase (reference
    PodMonitor.monitor_status)."""

    def __init__(self, k8s_client, pod_name, poll_interval=5):
        self._client = k8s_client
        self._pod_name = pod_name
        self._poll_interval = poll_interval

    def monitor_status(self, timeout=None, max_not_found=3):
        deadline = time.time() + timeout if timeout else None
        last_phase = None
        not_found = 0
        while True:
            pod = self._client.get_pod(self._pod_name)
            phase = _phase(pod)
            if pod is None:
                not_found += 1
                if not_found >= max_not_found:
                    # evicted/deleted pod: terminal, don't poll forever
                    logger.warning(
                        "Pod %s not found; giving up", self._pod_name
                    )
                    return "NotFound"
            else:
                not_found = 0
            if phase != last_phase:
                logger.info("Pod %s phase: %s", self._pod_name, phase)
                last_phase = phase
            if phase in _FINISHED_PHASES:
                return phase
            if deadline and time.time() > deadline:
                return phase
            time.sleep(self._poll_interval)


class EdlJobMonitor(object):
    """Monitor a whole job: master phase + log tailing (reference
    EdlJobMonitor.monitor_job_status)."""

    def __init__(self, k8s_client, poll_interval=5):
        self._client = k8s_client
        self._poll_interval = poll_interval

    def tail_master_log(self, since_seconds=None):
        try:
            return self._client.client.read_namespaced_pod_log(
                self._client.get_master_pod_name(),
                self._client.namespace,
                **(
                    {"since_seconds": since_seconds}
                    if since_seconds
                    else {}
                ),
            )
        except Exception as e:
            logger.warning("Cannot read master log: %s", e)
            return None

    def monitor_job_status(self, timeout=None):
        phase = PodMonitor(
            self._client,
            self._client.get_master_pod_name(),
            poll_interval=self._poll_interval,
        ).monitor_status(timeout=timeout)
        log = self.tail_master_log(since_seconds=60)
        if log:
            for line in log.splitlines()[-20:]:
                logger.info("[master] %s", line)
        if phase in ("Failed", "NotFound"):
            raise RuntimeError(
                "Job failed (master pod phase %s)" % phase
            )
        return phase
