"""Docker image builder for model-zoo jobs (reference
elasticdl/python/elasticdl/image_builder.py, 272 LoC): assemble a
Dockerfile that layers the model zoo (and its requirements) onto a base
image carrying the framework, then build/push via the docker CLI.

The docker binary is the gate: everything here raises a clear error when
it's absent, and `write_dockerfile` (pure file generation) is always
available and unit-tested."""

import os
import shutil
import subprocess
import tempfile

from elasticdl_tpu.common.log_utils import default_logger as logger

_FRAMEWORK_DIR = ".elasticdl_tpu_framework"

_DOCKERFILE = """\
FROM {base_image}
COPY . /model_zoo
RUN pip install --no-cache-dir {pypi_flag} -r /model_zoo/requirements.txt
# the framework itself rides in the build context so the job image can
# run `python -m elasticdl_tpu...` (the reference embeds the framework
# wheel the same way, image_builder.py)
ENV PYTHONPATH=/model_zoo:/model_zoo/{framework_dir}:$PYTHONPATH
{cluster_spec_line}
"""


def write_dockerfile(zoo_path, base_image="python:3.10",
                     extra_pypi_index="", cluster_spec=""):
    """Generate the zoo Dockerfile (reference
    image_builder._generate_dockerfile)."""
    pypi_flag = (
        "--extra-index-url %s" % extra_pypi_index
        if extra_pypi_index
        else ""
    )
    cluster_spec_line = (
        "COPY %s /cluster_spec/cluster_spec.py" % cluster_spec
        if cluster_spec
        else ""
    )
    content = _DOCKERFILE.format(
        base_image=base_image,
        pypi_flag=pypi_flag,
        cluster_spec_line=cluster_spec_line,
        framework_dir=_FRAMEWORK_DIR,
    )
    dockerfile = os.path.join(zoo_path, "Dockerfile")
    with open(dockerfile, "w") as f:
        f.write(content)
    return dockerfile


def _docker(*cmd):
    if shutil.which("docker") is None:
        raise RuntimeError(
            "docker is not installed; build the image on a machine with "
            "docker or use the local (no-image) job path"
        )
    logger.info("Running: docker %s", " ".join(cmd))
    subprocess.run(["docker", *cmd], check=True)


def _copy_framework_into_context(context_dir):
    """Vendor the installed elasticdl_tpu package into the build context
    so the image can run master/worker entrypoints."""
    import elasticdl_tpu

    src = os.path.dirname(os.path.abspath(elasticdl_tpu.__file__))
    dst = os.path.join(context_dir, _FRAMEWORK_DIR, "elasticdl_tpu")
    shutil.copytree(
        src, dst,
        ignore=shutil.ignore_patterns("__pycache__", "*.pyc"),
    )
    return dst


def build_image(zoo_path, image):
    """docker build the zoo directory via a TEMP build context (the
    user's zoo dir is never mutated — no vendored framework or generated
    Dockerfile lands in their source tree)."""
    with tempfile.TemporaryDirectory(prefix="edl_tpu_build_") as ctx:
        context_dir = os.path.join(ctx, "context")
        shutil.copytree(
            zoo_path, context_dir,
            ignore=shutil.ignore_patterns(
                "__pycache__", "*.pyc", _FRAMEWORK_DIR
            ),
        )
        dockerfile = os.path.join(context_dir, "Dockerfile")
        regenerate = not os.path.exists(dockerfile)
        if not regenerate and _FRAMEWORK_DIR not in open(dockerfile).read():
            logger.info(
                "Existing Dockerfile predates framework vendoring; "
                "regenerating it inside the build context"
            )
            regenerate = True
        if regenerate:
            write_dockerfile(context_dir)
        _copy_framework_into_context(context_dir)
        _docker("build", "-t", image, context_dir)


def push_image(image):
    _docker("push", image)
