"""The `elasticdl-tpu` client CLI (reference elasticdl_client/main.py:
29-80): `zoo init|build|push`, `train`, `evaluate`, `predict`.

`train/evaluate/predict` submit a master — as a Kubernetes pod when
`--image_name` is given (reference api.train → master pod via the k8s
API), or as a local in-process master otherwise (the no-cluster path the
TPU build adds so a laptop run needs zero infra)."""

import argparse
import sys

from elasticdl_tpu.client import api
from elasticdl_tpu.common.args import (
    add_common_params,
    add_master_params,
)


def _add_zoo_init_params(parser):
    parser.add_argument(
        "--base_image", default="python:3.10",
        help="Base docker image for the zoo",
    )
    parser.add_argument(
        "--extra_pypi_index", default="", help="Extra pip index URL"
    )
    parser.add_argument(
        "--cluster_spec", default="",
        help="Cluster spec module copied into the image",
    )
    parser.add_argument("--path", default=".", help="Zoo directory")


def _add_zoo_build_params(parser):
    parser.add_argument(
        "path", nargs="?", default=".", help="Zoo directory to build"
    )
    parser.add_argument(
        "--image", required=True, help="Target docker image name"
    )


def _add_zoo_push_params(parser):
    parser.add_argument("image", help="Docker image to push")


def _add_job_params(parser):
    add_common_params(parser)
    add_master_params(parser)
    parser.add_argument(
        "--image_name", default="",
        help="Job image; empty = run the master locally (no cluster)",
    )
    parser.add_argument(
        "--master_resource_request", default="cpu=0.1,memory=1024Mi"
    )
    parser.add_argument("--master_resource_limit", default="")
    parser.add_argument("--master_pod_priority", default="")
    parser.add_argument(
        "--detach", action="store_true",
        help="Don't monitor the submitted job",
    )


def build_argument_parser():
    parser = argparse.ArgumentParser(prog="elasticdl-tpu")
    subparsers = parser.add_subparsers(dest="command")
    subparsers.required = True

    zoo_parser = subparsers.add_parser(
        "zoo", help="Manage model-zoo images"
    )
    zoo_sub = zoo_parser.add_subparsers(dest="zoo_command")
    zoo_sub.required = True
    init_p = zoo_sub.add_parser("init", help="Initialize a model zoo")
    _add_zoo_init_params(init_p)
    init_p.set_defaults(func=api.init_zoo)
    build_p = zoo_sub.add_parser("build", help="Build the zoo image")
    _add_zoo_build_params(build_p)
    build_p.set_defaults(func=api.build_zoo)
    push_p = zoo_sub.add_parser("push", help="Push the zoo image")
    _add_zoo_push_params(push_p)
    push_p.set_defaults(func=api.push_zoo)

    train_p = subparsers.add_parser("train", help="Submit a training job")
    _add_job_params(train_p)
    train_p.set_defaults(func=api.train)

    eval_p = subparsers.add_parser(
        "evaluate", help="Submit an evaluation job"
    )
    _add_job_params(eval_p)
    eval_p.set_defaults(func=api.evaluate)

    pred_p = subparsers.add_parser(
        "predict", help="Submit a prediction job"
    )
    _add_job_params(pred_p)
    pred_p.set_defaults(func=api.predict)
    return parser


def main(argv=None):
    from elasticdl_tpu.common.platform_utils import (
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    parser = build_argument_parser()
    args, extra = parser.parse_known_args(args=argv)
    return args.func(args, extra) or 0


if __name__ == "__main__":
    sys.exit(main())
