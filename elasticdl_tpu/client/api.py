"""Client API: zoo scaffolding, image build/push, job submission
(reference elasticdl_client/api.py:31-234).

Job submission rebuilds the master command line from the parsed args
(the reference's `_submit_job`, api.py:179-234) and either creates the
master pod through the k8s API or — with no `--image_name` — execs the
master entrypoint in-process, which is the zero-infra path."""

import os

from elasticdl_tpu.common.args import build_arguments_from_parsed_result
from elasticdl_tpu.common.log_utils import default_logger as logger

# flags that belong to the client only, never to the master process
_CLIENT_ONLY_ARGS = {
    "command", "zoo_command", "func", "image_name", "detach",
    "master_resource_request", "master_resource_limit",
    "master_pod_priority",
}

_ZOO_TEMPLATE = '''\
"""Model-zoo module template. Export by convention:
custom_model / loss / optimizer / dataset_fn / eval_metrics_fn."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn


class MyModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["x"]
        return nn.Dense(1)(x)


def custom_model():
    return MyModel()


def loss(labels, predictions, sample_weights=None):
    err = (predictions.reshape(-1) - labels.reshape(-1)) ** 2
    if sample_weights is None:
        return jnp.mean(err)
    return jnp.sum(err * sample_weights) / jnp.maximum(
        jnp.sum(sample_weights), 1.0
    )


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, metadata):
    return dataset


def eval_metrics_fn():
    return {
        "mse": lambda labels, predictions: (
            (np.asarray(predictions).reshape(-1)
             - np.asarray(labels).reshape(-1)) ** 2
        )
    }
'''


# ------------------------------------------------------------------ zoo


def init_zoo(args, extra=None):
    """Scaffold a model-zoo directory (reference api.init_zoo,
    api.py:31-62): requirements + a template module + the Dockerfile
    seed."""
    path = args.path
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "requirements.txt"), "w") as f:
        f.write("jax\nflax\noptax\n")
    with open(os.path.join(path, "my_model.py"), "w") as f:
        f.write(_ZOO_TEMPLATE)
    from elasticdl_tpu.client.image_builder import write_dockerfile

    write_dockerfile(
        path,
        base_image=args.base_image,
        extra_pypi_index=args.extra_pypi_index,
        cluster_spec=args.cluster_spec,
    )
    logger.info("Initialized model zoo at %s", path)
    return 0


def build_zoo(args, extra=None):
    from elasticdl_tpu.client.image_builder import build_image

    build_image(args.path, args.image)
    return 0


def push_zoo(args, extra=None):
    from elasticdl_tpu.client.image_builder import push_image

    push_image(args.image)
    return 0


# ------------------------------------------------------------------ jobs


def train(args, extra=None):
    return _submit_job(args, extra, job_mode="train")


def evaluate(args, extra=None):
    _require(args.validation_data, "--validation_data")
    args.training_data = ""
    return _submit_job(args, extra, job_mode="evaluate")


def predict(args, extra=None):
    _require(args.prediction_data, "--prediction_data")
    args.training_data = ""
    args.validation_data = ""
    return _submit_job(args, extra, job_mode="predict")


def _require(value, flag):
    if not value:
        raise ValueError("%s is required for this command" % flag)


def build_master_args(args, extra=None):
    """Master command-line from the parsed client args (reference
    api._submit_job rebuilding `python -m ...master.main --…`)."""
    master_args = build_arguments_from_parsed_result(
        args, filter_args=_CLIENT_ONLY_ARGS
    )
    return master_args + list(extra or [])


def _submit_job(args, extra, job_mode):
    master_args = build_master_args(args, extra)
    if not args.image_name:
        # no-cluster path: run the master right here
        from elasticdl_tpu.master.main import main as master_main

        logger.info("Running local master (%s)", job_mode)
        return master_main(master_args)
    return _submit_master_pod(args, master_args)


def _submit_master_pod(args, master_args, core_api=None):
    """Create the master pod via the k8s API (reference
    elasticdl_client/common/k8s_client.py create_master)."""
    from elasticdl_tpu.common.args import parse_resource_spec
    from elasticdl_tpu.common.k8s_client import Client

    client = Client(
        image_name=args.image_name,
        namespace=args.namespace,
        job_name=args.job_name,
        core_api=core_api,
    )
    client.create_master_pod(
        # plain "python": resolved inside the job image, never the
        # client machine's interpreter path
        command=["python", "-m", "elasticdl_tpu.master.main"],
        args=master_args,
        resource_requests=parse_resource_spec(
            args.master_resource_request
        ),
        resource_limits=parse_resource_spec(args.master_resource_limit),
        priority_class=args.master_pod_priority or None,
        restart_policy=args.restart_policy,
        image_pull_policy=args.image_pull_policy,
    )
    logger.info(
        "Submitted master pod %s", client.get_master_pod_name()
    )
    if not args.detach:
        from elasticdl_tpu.client.job_monitor import EdlJobMonitor

        EdlJobMonitor(client).monitor_job_status()
    return 0
