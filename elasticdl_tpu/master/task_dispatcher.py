"""Dynamic data sharding: the master-owned task queue.

Behavioral parity with the reference's master/task_dispatcher.py:27-392 —
tasks are record ranges (shard_name, start, end) of ``records_per_task``
records; workers pull tasks, so the worker count is elastic by construction:

* per-epoch TRAINING task creation with shuffle; EVALUATION / PREDICTION /
  TRAIN_END_CALLBACK task types,
* todo / doing bookkeeping keyed by task_id with per-task start timestamps
  (feeds the straggler watchdog),
* failed tasks are re-queued at most ``MAX_TASK_RETRIES`` (=3) times,
* epoch rollover happens lazily inside ``get`` when the todo list drains,
* a deferred TRAIN_END_CALLBACK task (one shard of data) is appended after
  all training tasks finish so the worker can run train-end callbacks
  (SavedModel export) with real data,
* ``recover_tasks(worker_id)`` re-queues everything a dead worker was doing.

Beyond the reference: the dispatcher is crash-recoverable. With a
``state_store`` (master/state_store.py) attached, every lifecycle
transition is journaled write-ahead and ``restore()`` reconstructs
todo ∪ requeued-doing exactly after a master SIGKILL — including retry
counts, epoch position, pending deferred train-end work, and the last
reported model version. Requeued-doing tasks remember their pre-crash
task ids (``_recovered_doing``) so a surviving worker's late completion
report is reconciled instead of double-dispatching the range.

TF-free: callbacks are the framework's own (elasticdl_tpu/api/callbacks.py);
`stop_training` lives on the dispatcher itself and is toggled by
MaxStepsStopping-style callbacks.
"""

import random
import threading
import time

from elasticdl_tpu.common.constants import (
    MAX_TASK_RETRIES,
    TaskExecCounterKey,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.analysis.typestate import JournalProtocol

#: Declared journal protocol: the single source of truth edl-lint
#: (EDL701-EDL704) verifies restore() and every _journal() site
#: against, and the machine the spec-derived crash-point replay
#: battery walks (tests/test_protocol_batteries.py). Task lifecycle is
#: per-id (``entity_key``): ids are dispatched once (the counter only
#: grows), a failure requeues the RANGE under a future fresh id, and
#: ``done_recovered`` reconciles an id dispatched before a crash — its
#: ``dispatch`` may live in an earlier journal incarnation, hence the
#: liberal from-set.
PROTOCOL = JournalProtocol(
    name="task_dispatcher",
    kind_key="ev",
    emit="_journal",
    replay="restore",
    states=("idle", "doing", "done"),
    initial="idle",
    terminal=("done",),
    events={
        "create": {"requires": ("task_type", "tasks"),
                   "optional": ("epoch",)},
        "dispatch": {"entity_key": "id", "from": ("idle",),
                     "to": "doing", "requires": ("task",),
                     "optional": ("worker",)},
        "done": {"entity_key": "id", "from": ("doing",),
                 "to": "done", "requires": ("task",)},
        "done_recovered": {"entity_key": "id", "from": "*",
                           "to": "done", "requires": ("task",)},
        "fail": {"entity_key": "id", "from": ("doing",),
                 "to": "idle", "requires": ("task",)},
        "stop": {},
        "version": {"requires": ("v",)},
        "deferred_add": {},
        "deferred_invoked": {},
    },
    recoverable={
        "idle": "restore() rebuilds todo from snapshot + journal",
        "doing": "restore() requeues in-flight ranges and parks the "
                 "old ids in _recovered_doing for reconciliation",
        "done": "nothing to resume",
    },
)


class TaskType(object):
    """Task types (reference: proto enum elasticdl.proto TaskType)."""

    TRAINING = "TRAINING"
    EVALUATION = "EVALUATION"
    PREDICTION = "PREDICTION"
    WAIT = "WAIT"
    TRAIN_END_CALLBACK = "TRAIN_END_CALLBACK"


class Task(object):
    """A record-range work item (reference _Task)."""

    __slots__ = ("shard_name", "start", "end", "type", "model_version",
                 "extended_config")

    def __init__(self, shard_name, start, end, type, model_version=-1,
                 **kwargs):
        self.shard_name = shard_name
        self.start = start
        self.end = end
        self.type = type
        self.model_version = model_version
        self.extended_config = kwargs

    def _info(self):
        return (
            self.shard_name, self.start, self.end, self.type,
            self.model_version,
        )

    def __repr__(self):
        return "Task(%s[%d:%d], %s, v%d)" % self._info()


def _payload(task):
    """JSON-serializable journal form of a task."""
    return list(task._info())


def _task_from_payload(p):
    return Task(p[0], p[1], p[2], p[3], model_version=p[4])


def _key(payload_or_task):
    if isinstance(payload_or_task, Task):
        return payload_or_task._info()
    return tuple(payload_or_task)


class JobCounter(object):
    def __init__(self, total_records=0, failed_records=0):
        self.total_records = total_records
        self.failed_records = failed_records


class TaskDispatcher(object):
    def __init__(
        self,
        training_shards,
        evaluation_shards,
        prediction_shards,
        records_per_task,
        num_epochs,
        callbacks_list=None,
        state_store=None,
    ):
        self._lock = threading.Lock()
        self._num_epochs = num_epochs
        self._epoch = 0
        self._training_shards = training_shards
        self._evaluation_shards = evaluation_shards
        self._prediction_shards = prediction_shards
        self._records_per_task = records_per_task
        self._callbacks_list = callbacks_list
        self.stop_training = False

        self._todo = []
        self._doing = {}  # task_id -> (worker_id, task, start_time)
        self._task_id = 0
        self._eval_todo = []
        self._evaluation_service = None
        self._tasks_done_deferred_callbacks = []
        self._job_counters = {}
        # retry counts keyed by task payload (shard, start, end, type,
        # model_version) — payload keys survive the journal round-trip,
        # where object identity cannot
        self._task_retry_count = {}
        self._state_store = state_store
        # pre-crash task_id -> payload key of requeued-doing tasks, for
        # reconciling a surviving worker's late completion report
        self._recovered_doing = {}
        self._restored = False
        self._train_end_handled = False
        self.model_version = 0
        # observability (master/recovery gauges)
        self.requeued_on_recovery = 0
        self.recovered_late_completions = 0

        if state_store is not None and state_store.has_state():
            snapshot, events = state_store.load()
            self.restore(snapshot, events)
        elif self._training_shards:
            logger.info("Starting epoch %d", self._epoch)
            self.create_tasks(TaskType.TRAINING)
        elif self._evaluation_shards:
            self.create_tasks(TaskType.EVALUATION)
        elif self._prediction_shards:
            self.create_tasks(TaskType.PREDICTION)

    # ------------------------------------------------------------ journal

    def _journal(self, event):
        """Write-ahead one lifecycle event; compact when the store asks.
        Callers either hold self._lock or run single-threaded (ctor)."""
        if self._state_store is None:
            return
        if self._state_store.append(event):
            self._state_store.write_snapshot(self._snapshot_locked())

    def reset_job_counters(self, task_type):
        self._job_counters[task_type] = JobCounter()

    def queue_depths(self):
        """(todo, doing, eval_todo) under the lock — the master's
        /metrics exposition reads queue pressure through this instead
        of racing the raw lists."""
        with self._lock:
            return (len(self._todo), len(self._doing),
                    len(self._eval_todo))

    def create_tasks(self, task_type, model_version=-1):
        """Public entry: callers outside the dispatcher (the evaluation
        service's trigger threads) do NOT hold the lock, but they race
        workers popping the queues — take it here. Internal callers
        already under the lock use _create_tasks_locked directly."""
        with self._lock:
            return self._create_tasks_locked(task_type, model_version)

    def _create_tasks_locked(self, task_type, model_version=-1):
        logger.info(
            "Creating a new set of %s tasks for model version %d",
            task_type.lower(),
            model_version,
        )
        self.reset_job_counters(task_type)
        if task_type == TaskType.TRAINING:
            shards = self._training_shards
        elif task_type == TaskType.EVALUATION:
            shards = self._evaluation_shards
        else:
            shards = self._prediction_shards
        tasks = []
        counter = self._job_counters[task_type]
        for shard_name, (start_ind, num_records) in shards.items():
            max_ind = start_ind + num_records
            counter.total_records += num_records
            for task_start in range(start_ind, max_ind,
                                    self._records_per_task):
                tasks.append(
                    Task(
                        shard_name=shard_name,
                        start=task_start,
                        end=min(task_start + self._records_per_task, max_ind),
                        type=task_type,
                        model_version=model_version,
                    )
                )
        if task_type == TaskType.TRAINING:
            random.shuffle(tasks)
        self._journal({
            "ev": "create",
            "task_type": task_type,
            "epoch": self._epoch,
            "tasks": [_payload(t) for t in tasks],
        })
        if task_type == TaskType.EVALUATION:
            self._eval_todo.extend(tasks)
        else:
            self._todo.extend(tasks)
        logger.info("%d tasks created with total of %d records.",
                    len(tasks), counter.total_records)
        return len(tasks)

    def get_eval_task(self, worker_id):
        with self._lock:
            if not self._eval_todo:
                return -1, None
            self._task_id += 1
            task = self._eval_todo.pop()
            self._journal({
                "ev": "dispatch", "id": self._task_id,
                "worker": worker_id, "task": _payload(task),
            })
            self._doing[self._task_id] = (worker_id, task, time.time())
            return self._task_id, task

    def _create_train_end_callback_task_locked(self):
        """Append one TRAIN_END_CALLBACK task carrying the first shard's
        first task-range of data (reference :219-250)."""
        if not self._training_shards:
            return
        self.reset_job_counters(TaskType.TRAIN_END_CALLBACK)
        shard_name, (start_ind, num_records) = next(
            iter(self._training_shards.items())
        )
        task = Task(
            shard_name=shard_name,
            start=start_ind,
            end=start_ind + min(self._records_per_task, num_records),
            type=TaskType.TRAIN_END_CALLBACK,
        )
        self._journal({
            "ev": "create",
            "task_type": TaskType.TRAIN_END_CALLBACK,
            "epoch": self._epoch,
            "tasks": [_payload(task)],
        })
        self._todo.append(task)

    def add_deferred_callback_create_train_end_task(self):
        # runs on the master wait-loop thread while worker RPCs mutate
        # the same state — and after a restore the deferred callback (or
        # the train-end task it creates) is already part of the
        # recovered state, so re-adding it would run the train-end
        # export twice; both the check and the append belong under the
        # lock (the unlocked append was edl-lint EDL001's first catch)
        with self._lock:
            if self._restored and (
                self._tasks_done_deferred_callbacks
                or self._train_end_handled
            ):
                return
            self._journal({"ev": "deferred_add"})
            self._tasks_done_deferred_callbacks.append(
                self._create_train_end_callback_task_locked
            )

    def invoke_deferred_callback(self):
        with self._lock:
            if not self._tasks_done_deferred_callbacks:
                return False
            self._journal({"ev": "deferred_invoked"})
            callback = self._tasks_done_deferred_callbacks.pop()
            callback()
            return True

    def get(self, worker_id):
        """Pop the next (task_id, task); starts a new epoch lazily when the
        todo list drains (reference :272-297)."""
        with self._lock:
            if (
                not self._todo
                and not self.stop_training
                and self._epoch < self._num_epochs - 1
            ):
                self._epoch += 1
                self._create_tasks_locked(TaskType.TRAINING)
                logger.info("Starting epoch %d", self._epoch)

            if not self._todo:
                return -1, None

            self._task_id += 1
            task = self._todo.pop()
            self._journal({
                "ev": "dispatch", "id": self._task_id,
                "worker": worker_id, "task": _payload(task),
            })
            self._doing[self._task_id] = (worker_id, task, time.time())
            return self._task_id, task

    def report(self, task_id, success, exec_counters=None):
        """Mark a doing task finished or failed; failed tasks re-queue unless
        they exceeded MAX_TASK_RETRIES (reference :299-348).

        Returns (elapsed_time, task, worker_id)."""
        evaluation_task_completed = False
        eval_service = None
        with self._lock:
            worker_id, task, start_time = self._doing.pop(
                task_id, (-1, None, -1)
            )
            if task and exec_counters:
                self._job_counters[task.type].failed_records += (
                    exec_counters.get(TaskExecCounterKey.FAIL_COUNT, 0)
                )
            if not task:
                if task_id in self._recovered_doing:
                    worker_id = self._reconcile_recovered(
                        task_id, success
                    )
                else:
                    logger.warning("Unknown task_id: %d", task_id)
            elif not success:
                logger.warning("Task %d of %s failed", task_id, task.type)
                self._journal({
                    "ev": "fail", "id": task_id, "task": _payload(task),
                })
                if not self.check_exceed_max_task_retries(task):
                    # Deviation from the reference (:320-327): it re-queues
                    # failed PREDICTION tasks into the eval queue, which
                    # prediction jobs never drain — a job hang. Here every
                    # non-eval task returns to the main todo queue.
                    if task.type == TaskType.EVALUATION:
                        self._eval_todo.append(task)
                    else:
                        self._todo.append(task)
            elif (
                task.type == TaskType.EVALUATION
                and self._evaluation_service is not None
            ):
                self._journal({
                    "ev": "done", "id": task_id, "task": _payload(task),
                })
                evaluation_task_completed = True
                eval_service = self._evaluation_service
            else:
                self._journal({
                    "ev": "done", "id": task_id, "task": _payload(task),
                })
                self._call_on_task_end(task)
                logger.info(
                    "Task:%d completed, %d remaining tasks",
                    task_id,
                    len(self._todo) + len(self._doing),
                )

            if success:
                if task:
                    self._task_retry_count.pop(_key(task), None)
                    if task.type == TaskType.TRAIN_END_CALLBACK:
                        self._train_end_handled = True
                if self.stop_training and self._todo:
                    self._journal({"ev": "stop"})
                    self._todo = []

        # OUTSIDE the lock: complete_task re-enters the dispatcher
        # (try_to_create_new_job -> create_tasks takes self._lock), and
        # calling another object's methods while holding our own lock
        # is the AB/BA deadlock shape the router/master interplay must
        # never grow
        if evaluation_task_completed:
            eval_service.complete_task()

        return (time.time() - start_time), task, worker_id

    def _reconcile_recovered(self, task_id, success):
        """A report arrived for a task dispatched BEFORE the master
        crashed. Its range was requeued on restore; a success report means
        the surviving worker finished it after all — pull the duplicate
        back out of todo so the range runs exactly once. Returns the
        pre-crash worker id (the reporter) so the servicer's per-worker
        gauges keep their identity. (Caller holds the lock.)"""
        worker_id, key = self._recovered_doing.pop(task_id)
        if not success:
            # already requeued at restore; nothing more to do
            logger.info(
                "Pre-crash task %d reported failed; already requeued",
                task_id,
            )
            return worker_id
        for queue in (self._todo, self._eval_todo):
            for i, queued in enumerate(queue):
                if _key(queued) == key:
                    task = queue.pop(i)
                    self._journal({
                        "ev": "done_recovered", "id": task_id,
                        "task": _payload(task),
                    })
                    self._task_retry_count.pop(key, None)
                    self.recovered_late_completions += 1
                    self._call_on_task_end(task)
                    logger.info(
                        "Pre-crash task %d completed by its worker; "
                        "de-duplicated from todo", task_id,
                    )
                    return worker_id
        # the requeued copy was already re-dispatched: let that execution
        # finish normally; the range ran (at most) twice — unavoidable
        # once both executions are in flight
        logger.warning(
            "Pre-crash task %d completed but its range was already "
            "re-dispatched", task_id,
        )
        return worker_id

    def check_exceed_max_task_retries(self, task):
        key = _key(task)
        self._task_retry_count.setdefault(key, 1)
        self._task_retry_count[key] += 1
        if self._task_retry_count[key] > MAX_TASK_RETRIES:
            logger.error(
                "A %s task failed with %d retries", task.type,
                MAX_TASK_RETRIES,
            )
            self._task_retry_count.pop(key, None)
            return True
        return False

    def record_model_version(self, version):
        """Journal the latest reported model version (the servicer owns
        the live max; this persists it for eval-trigger dedup across a
        master restart)."""
        with self._lock:
            if version > self.model_version:
                self.model_version = version
                self._journal({"ev": "version", "v": int(version)})

    def finished(self):
        """Job-complete test, read by servicer threads while dispatch/
        report mutate the queues — an unlocked read can see `_todo`
        empty and `_doing` already popped mid-report and tell a worker
        JOB_COMPLETE while the report is about to requeue a failed
        task (edl-lint EDL002)."""
        with self._lock:
            return (
                not self._todo
                and not self._eval_todo
                and not self._doing
            )

    def recover_tasks(self, worker_id):
        """Re-queue all doing tasks of a dead worker (reference :365-377)."""
        with self._lock:
            ids = [
                tid
                for tid, (wid, _, _) in self._doing.items()
                if wid == worker_id
            ]
        for tid in ids:
            self.report(tid, False)

    def set_evaluation_service(self, evaluation_service):
        with self._lock:
            self._evaluation_service = evaluation_service
            eval_only = (
                bool(self._evaluation_shards)
                and not self._training_shards
            )
            n_eval = len(self._eval_todo)
        # init takes the eval service's own lock; never nest it under
        # ours (see report() for the lock-ordering rule)
        if eval_only:
            evaluation_service.init_eval_only_job(n_eval)

    def _call_on_task_end(self, task):
        if self._callbacks_list:
            for callback in self._callbacks_list.callbacks:
                if hasattr(callback, "on_task_end"):
                    callback.on_task_end(task)

    # ------------------------------------------------- snapshot / restore

    def snapshot(self):
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self):
        return {
            "format": 1,
            "epoch": self._epoch,
            "task_id": self._task_id,
            "todo": [_payload(t) for t in self._todo],
            "eval_todo": [_payload(t) for t in self._eval_todo],
            "doing": [
                [tid, wid, _payload(task)]
                for tid, (wid, task, _) in self._doing.items()
            ],
            "retry": [
                [list(k), v] for k, v in self._task_retry_count.items()
            ],
            "stop_training": self.stop_training,
            "model_version": self.model_version,
            "deferred_train_end": len(self._tasks_done_deferred_callbacks),
            "train_end_handled": self._train_end_handled,
            # un-reconciled pre-crash dispatches survive a SECOND crash
            "recovered_doing": [
                [tid, wid, list(key)]
                for tid, (wid, key) in self._recovered_doing.items()
            ],
        }

    def restore(self, snapshot, events):
        """Rebuild exact dispatcher state from a snapshot plus journal
        replay. Post-condition: todo = snapshot-todo ∪ requeued-doing
        (pre-crash in-flight ranges re-run; their old ids are kept in
        _recovered_doing for late-report reconciliation), retry counts
        and epoch position carry over, and no record range is lost."""
        snapshot = snapshot or {}
        epoch = snapshot.get("epoch", 0)
        task_id = snapshot.get("task_id", 0)
        todo = [list(p) for p in snapshot.get("todo", [])]
        eval_todo = [list(p) for p in snapshot.get("eval_todo", [])]
        doing = {
            tid: (wid, list(p))
            for tid, wid, p in snapshot.get("doing", [])
        }
        retry = {
            tuple(k): v for k, v in snapshot.get("retry", [])
        }
        stop_training = snapshot.get("stop_training", False)
        model_version = snapshot.get("model_version", 0)
        deferred = snapshot.get("deferred_train_end", 0)
        train_end_handled = snapshot.get("train_end_handled", False)
        recovered = {
            tid: (wid, tuple(key))
            for tid, wid, key in snapshot.get("recovered_doing", [])
        }

        def remove_one(queue, key):
            for i, p in enumerate(queue):
                if _key(p) == key:
                    queue.pop(i)
                    return True
            return False

        for ev in events:
            kind = ev.get("ev")
            if kind == "create":
                # idempotent under snapshot/journal overlap (a crash
                # between write_snapshot and the journal truncate
                # replays the full journal against a snapshot that
                # already incorporates it): a task whose range is
                # still queued or in flight is not re-added — later
                # dispatch/done/fail events re-consume the rest
                if ev["task_type"] == TaskType.EVALUATION:
                    queue = eval_todo
                else:
                    if ev["task_type"] == TaskType.TRAINING:
                        epoch = ev.get("epoch", epoch)
                    queue = todo
                present = {_key(p) for p in queue}
                present |= {_key(p) for _w, p in doing.values()}
                queue.extend(
                    p for p in ev["tasks"] if _key(p) not in present
                )
            elif kind == "dispatch":
                p = ev["task"]
                queue = (
                    eval_todo if p[3] == TaskType.EVALUATION else todo
                )
                # idempotent under snapshot/journal overlap: a dispatch
                # whose task is absent only claims the id
                remove_one(queue, _key(p))
                doing[ev["id"]] = (ev.get("worker", -1), p)
                task_id = max(task_id, ev["id"])
            elif kind == "done":
                _, p = doing.pop(ev["id"], (None, None))
                retry.pop(_key(ev["task"]), None)
                if ev["task"][3] == TaskType.TRAIN_END_CALLBACK:
                    train_end_handled = True
            elif kind == "done_recovered":
                p = ev["task"]
                queue = (
                    eval_todo if p[3] == TaskType.EVALUATION else todo
                )
                remove_one(queue, _key(p))
                retry.pop(_key(p), None)
                recovered.pop(ev["id"], None)
            elif kind == "fail":
                doing.pop(ev["id"], None)
                p = ev["task"]
                key = _key(p)
                retry.setdefault(key, 1)
                retry[key] += 1
                if retry[key] > MAX_TASK_RETRIES:
                    retry.pop(key, None)  # permanently failed
                elif p[3] == TaskType.EVALUATION:
                    eval_todo.append(p)
                else:
                    todo.append(p)
            elif kind == "stop":
                stop_training = True
                todo = []
            elif kind == "version":
                model_version = max(model_version, ev["v"])
            elif kind == "deferred_add":
                deferred += 1
            elif kind == "deferred_invoked":
                deferred -= 1
                train_end_handled = True
            else:
                logger.warning("Unknown journal event %r", kind)

        # materialize: requeue every pre-crash in-flight task and remember
        # its old id for late-report reconciliation
        self._epoch = epoch
        self._task_id = task_id
        self._todo = [_task_from_payload(p) for p in todo]
        self._eval_todo = [_task_from_payload(p) for p in eval_todo]
        self._doing = {}
        self._recovered_doing = dict(recovered)
        for tid, (wid, p) in sorted(doing.items()):
            task = _task_from_payload(p)
            if task.type == TaskType.EVALUATION:
                self._eval_todo.append(task)
            else:
                self._todo.append(task)
            self._recovered_doing[tid] = (wid, _key(p))
        self.requeued_on_recovery = len(doing)
        self._task_retry_count = dict(retry)
        self.stop_training = stop_training
        self.model_version = model_version
        self._train_end_handled = train_end_handled
        self._tasks_done_deferred_callbacks = [
            self._create_train_end_callback_task_locked
        ] * max(0, deferred)
        # job counters: totals are derivable from the shard dict; failed
        # counts are best-effort observability and reset on restart
        for task_type, shards in (
            (TaskType.TRAINING, self._training_shards),
            (TaskType.EVALUATION, self._evaluation_shards),
            (TaskType.PREDICTION, self._prediction_shards),
        ):
            if shards:
                self.reset_job_counters(task_type)
                self._job_counters[task_type].total_records = sum(
                    n for _, n in shards.values()
                )
        self._restored = True
        logger.info(
            "Dispatcher restored: epoch %d, %d todo, %d eval, %d "
            "requeued from pre-crash doing, %d retry entries",
            self._epoch, len(self._todo) - len(self._recovered_doing),
            len(self._eval_todo), self.requeued_on_recovery,
            len(self._task_retry_count),
        )
        # a compacted snapshot right away bounds the next crash's replay
        if self._state_store is not None:
            self._state_store.write_snapshot(self._snapshot_locked())

    # introspection helpers for the servicer / watchdog
    @property
    def epoch(self):
        return self._epoch

    def doing_tasks(self):
        with self._lock:
            return dict(self._doing)
