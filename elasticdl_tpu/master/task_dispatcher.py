"""Dynamic data sharding: the master-owned task queue.

Behavioral parity with the reference's master/task_dispatcher.py:27-392 —
tasks are record ranges (shard_name, start, end) of ``records_per_task``
records; workers pull tasks, so the worker count is elastic by construction:

* per-epoch TRAINING task creation with shuffle; EVALUATION / PREDICTION /
  TRAIN_END_CALLBACK task types,
* todo / doing bookkeeping keyed by task_id with per-task start timestamps
  (feeds the straggler watchdog),
* failed tasks are re-queued at most ``MAX_TASK_RETRIES`` (=3) times,
* epoch rollover happens lazily inside ``get`` when the todo list drains,
* a deferred TRAIN_END_CALLBACK task (one shard of data) is appended after
  all training tasks finish so the worker can run train-end callbacks
  (SavedModel export) with real data,
* ``recover_tasks(worker_id)`` re-queues everything a dead worker was doing.

TF-free: callbacks are the framework's own (elasticdl_tpu/api/callbacks.py);
`stop_training` lives on the dispatcher itself and is toggled by
MaxStepsStopping-style callbacks.
"""

import random
import threading
import time

from elasticdl_tpu.common.constants import (
    MAX_TASK_RETRIES,
    TaskExecCounterKey,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


class TaskType(object):
    """Task types (reference: proto enum elasticdl.proto TaskType)."""

    TRAINING = "TRAINING"
    EVALUATION = "EVALUATION"
    PREDICTION = "PREDICTION"
    WAIT = "WAIT"
    TRAIN_END_CALLBACK = "TRAIN_END_CALLBACK"


class Task(object):
    """A record-range work item (reference _Task)."""

    __slots__ = ("shard_name", "start", "end", "type", "model_version",
                 "extended_config")

    def __init__(self, shard_name, start, end, type, model_version=-1,
                 **kwargs):
        self.shard_name = shard_name
        self.start = start
        self.end = end
        self.type = type
        self.model_version = model_version
        self.extended_config = kwargs

    def _info(self):
        return (
            self.shard_name, self.start, self.end, self.type,
            self.model_version,
        )

    def __repr__(self):
        return "Task(%s[%d:%d], %s, v%d)" % self._info()


class JobCounter(object):
    def __init__(self, total_records=0, failed_records=0):
        self.total_records = total_records
        self.failed_records = failed_records


class TaskDispatcher(object):
    def __init__(
        self,
        training_shards,
        evaluation_shards,
        prediction_shards,
        records_per_task,
        num_epochs,
        callbacks_list=None,
    ):
        self._lock = threading.Lock()
        self._num_epochs = num_epochs
        self._epoch = 0
        self._training_shards = training_shards
        self._evaluation_shards = evaluation_shards
        self._prediction_shards = prediction_shards
        self._records_per_task = records_per_task
        self._callbacks_list = callbacks_list
        self.stop_training = False

        self._todo = []
        self._doing = {}  # task_id -> (worker_id, task, start_time)
        self._task_id = 0
        self._eval_todo = []
        self._evaluation_service = None
        self._tasks_done_deferred_callbacks = []
        self._job_counters = {}
        self._task_retry_count = {}

        if self._training_shards:
            logger.info("Starting epoch %d", self._epoch)
            self.create_tasks(TaskType.TRAINING)
        elif self._evaluation_shards:
            self.create_tasks(TaskType.EVALUATION)
        elif self._prediction_shards:
            self.create_tasks(TaskType.PREDICTION)

    def reset_job_counters(self, task_type):
        self._job_counters[task_type] = JobCounter()

    def create_tasks(self, task_type, model_version=-1):
        logger.info(
            "Creating a new set of %s tasks for model version %d",
            task_type.lower(),
            model_version,
        )
        self.reset_job_counters(task_type)
        if task_type == TaskType.TRAINING:
            shards = self._training_shards
        elif task_type == TaskType.EVALUATION:
            shards = self._evaluation_shards
        else:
            shards = self._prediction_shards
        tasks = []
        counter = self._job_counters[task_type]
        for shard_name, (start_ind, num_records) in shards.items():
            max_ind = start_ind + num_records
            counter.total_records += num_records
            for task_start in range(start_ind, max_ind,
                                    self._records_per_task):
                tasks.append(
                    Task(
                        shard_name=shard_name,
                        start=task_start,
                        end=min(task_start + self._records_per_task, max_ind),
                        type=task_type,
                        model_version=model_version,
                    )
                )
        if task_type == TaskType.TRAINING:
            random.shuffle(tasks)
            self._todo.extend(tasks)
        elif task_type == TaskType.EVALUATION:
            self._eval_todo.extend(tasks)
        else:
            self._todo.extend(tasks)
        logger.info("%d tasks created with total of %d records.",
                    len(tasks), counter.total_records)
        return len(tasks)

    def get_eval_task(self, worker_id):
        with self._lock:
            if not self._eval_todo:
                return -1, None
            self._task_id += 1
            task = self._eval_todo.pop()
            self._doing[self._task_id] = (worker_id, task, time.time())
            return self._task_id, task

    def _create_train_end_callback_task(self):
        """Append one TRAIN_END_CALLBACK task carrying the first shard's
        first task-range of data (reference :219-250)."""
        if not self._training_shards:
            return
        self.reset_job_counters(TaskType.TRAIN_END_CALLBACK)
        shard_name, (start_ind, num_records) = next(
            iter(self._training_shards.items())
        )
        self._todo.append(
            Task(
                shard_name=shard_name,
                start=start_ind,
                end=start_ind + min(self._records_per_task, num_records),
                type=TaskType.TRAIN_END_CALLBACK,
            )
        )

    def add_deferred_callback_create_train_end_task(self):
        self._tasks_done_deferred_callbacks.append(
            self._create_train_end_callback_task
        )

    def invoke_deferred_callback(self):
        with self._lock:
            if not self._tasks_done_deferred_callbacks:
                return False
            callback = self._tasks_done_deferred_callbacks.pop()
            callback()
            return True

    def get(self, worker_id):
        """Pop the next (task_id, task); starts a new epoch lazily when the
        todo list drains (reference :272-297)."""
        with self._lock:
            if (
                not self._todo
                and not self.stop_training
                and self._epoch < self._num_epochs - 1
            ):
                self._epoch += 1
                self.create_tasks(TaskType.TRAINING)
                logger.info("Starting epoch %d", self._epoch)

            if not self._todo:
                return -1, None

            self._task_id += 1
            task = self._todo.pop()
            self._doing[self._task_id] = (worker_id, task, time.time())
            return self._task_id, task

    def report(self, task_id, success, exec_counters=None):
        """Mark a doing task finished or failed; failed tasks re-queue unless
        they exceeded MAX_TASK_RETRIES (reference :299-348).

        Returns (elapsed_time, task, worker_id)."""
        evaluation_task_completed = False
        with self._lock:
            worker_id, task, start_time = self._doing.pop(
                task_id, (-1, None, -1)
            )
            if task and exec_counters:
                self._job_counters[task.type].failed_records += (
                    exec_counters.get(TaskExecCounterKey.FAIL_COUNT, 0)
                )
            if not task:
                logger.warning("Unknown task_id: %d", task_id)
            elif not success:
                logger.warning("Task %d of %s failed", task_id, task.type)
                if not self.check_exceed_max_task_retries(task):
                    # Deviation from the reference (:320-327): it re-queues
                    # failed PREDICTION tasks into the eval queue, which
                    # prediction jobs never drain — a job hang. Here every
                    # non-eval task returns to the main todo queue.
                    if task.type == TaskType.EVALUATION:
                        self._eval_todo.append(task)
                    else:
                        self._todo.append(task)
            elif (
                task.type == TaskType.EVALUATION
                and self._evaluation_service is not None
            ):
                evaluation_task_completed = True
            else:
                self._call_on_task_end(task)
                logger.info(
                    "Task:%d completed, %d remaining tasks",
                    task_id,
                    len(self._todo) + len(self._doing),
                )
            if evaluation_task_completed:
                self._evaluation_service.complete_task()

            if success:
                self._task_retry_count.pop(task, None)
                if self.stop_training:
                    self._todo = []

        return (time.time() - start_time), task, worker_id

    def check_exceed_max_task_retries(self, task):
        self._task_retry_count.setdefault(task, 1)
        self._task_retry_count[task] += 1
        if self._task_retry_count[task] > MAX_TASK_RETRIES:
            logger.error(
                "A %s task failed with %d retries", task.type,
                MAX_TASK_RETRIES,
            )
            return True
        return False

    def finished(self):
        return not self._todo and not self._eval_todo and not self._doing

    def recover_tasks(self, worker_id):
        """Re-queue all doing tasks of a dead worker (reference :365-377)."""
        with self._lock:
            ids = [
                tid
                for tid, (wid, _, _) in self._doing.items()
                if wid == worker_id
            ]
        for tid in ids:
            self.report(tid, False)

    def set_evaluation_service(self, evaluation_service):
        with self._lock:
            self._evaluation_service = evaluation_service
            if self._evaluation_shards and not self._training_shards:
                evaluation_service.init_eval_only_job(len(self._eval_todo))

    def _call_on_task_end(self, task):
        if self._callbacks_list:
            for callback in self._callbacks_list.callbacks:
                if hasattr(callback, "on_task_end"):
                    callback.on_task_end(task)

    # introspection helpers for the servicer / watchdog
    @property
    def epoch(self):
        return self._epoch

    def doing_tasks(self):
        with self._lock:
            return dict(self._doing)
