"""Evaluation service: time- and step-based eval job creation + master-side
metric aggregation.

Behavioral parity with the reference's master/evaluation_service.py:24-235:
* time-based trigger thread (start_delay_secs / throttle_secs),
* step-based trigger keyed to the model version reported by the compute
  plane (reference: the PS reports every `eval_steps`; here the worker
  reports its step count via report_version),
* one EvaluationJob at a time; further requested versions queue up,
* workers report raw model outputs + labels; the master aggregates
  (training/metrics.MetricsAggregator replaces Keras metric objects),
* on job completion metrics go to the metrics writer (TensorBoard service
  equivalent) and the log.
"""

import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.tensor_utils import deserialize_ndarray_dict
from elasticdl_tpu.master.task_dispatcher import TaskType
from elasticdl_tpu.training.metrics import MetricsAggregator


class EvaluationJob(object):
    def __init__(self, metrics_dict, model_version, total_tasks=-1):
        self.model_version = model_version
        self._total_tasks = total_tasks
        self._completed_tasks = 0
        self._aggregator = MetricsAggregator(metrics_dict)

    def complete_task(self):
        self._completed_tasks += 1

    def finished(self):
        return self._completed_tasks >= self._total_tasks

    def report_evaluation_metrics(self, model_outputs_bytes, labels_bytes):
        outputs = deserialize_ndarray_dict(model_outputs_bytes)
        labels_d = deserialize_ndarray_dict(labels_bytes)
        labels = labels_d.get("labels")
        # single-output models report under "output"; multi-output models
        # report one tensor per named output
        if set(outputs) == {"output"}:
            outputs = outputs["output"]
        self._aggregator.update(labels, outputs)
        return True

    def get_evaluation_summary(self):
        return self._aggregator.result()


class _EvaluationTrigger(threading.Thread):
    """Periodic time-based eval task creation (reference :65-97)."""

    def __init__(self, eval_service, start_delay_secs, throttle_secs):
        super().__init__(daemon=True)
        self._eval_service = eval_service
        self._stopper = threading.Event()
        self._throttle_secs = throttle_secs
        self._eval_min_time = time.time() + start_delay_secs

    def stop(self):
        self._stopper.set()

    def _wait_enough_time(self, cur, prev_start):
        if cur < self._eval_min_time:
            return False
        if prev_start != -1 and cur - prev_start < self._throttle_secs:
            return False
        return True

    def run(self):
        prev_start = -1
        while not self._stopper.is_set():
            now = time.time()
            if self._wait_enough_time(now, prev_start):
                self._eval_service.add_evaluation_task(
                    is_time_based_eval=True
                )
                prev_start = now
            self._stopper.wait(1.0)


class EvaluationService(object):
    def __init__(
        self,
        metrics_writer,
        task_d,
        start_delay_secs,
        throttle_secs,
        eval_steps,
        eval_only,
        eval_metrics_fn,
    ):
        self._metrics_writer = metrics_writer
        self._task_d = task_d
        # reentrant: complete_task -> try_to_create_new_job both lock
        self._lock = threading.RLock()
        self._eval_job = None
        self.trigger = _EvaluationTrigger(
            self, start_delay_secs, throttle_secs
        )
        self._time_based_eval = throttle_secs > 0
        self._eval_steps = eval_steps
        self._eval_checkpoint_versions = []
        self._last_eval_checkpoint_version = -1
        self._eval_only = eval_only
        self._eval_metrics_fn = eval_metrics_fn
        self._master_servicer = None
        self.completed_job_metrics = []  # [(version, {name: value})]

    def start(self):
        if self._time_based_eval and not self._eval_only:
            self.trigger.start()

    def stop(self):
        if self._time_based_eval and not self._eval_only:
            self.trigger.stop()

    def set_master_servicer(self, master_servicer):
        self._master_servicer = master_servicer

    def init_eval_only_job(self, num_task):
        # the trigger thread may already be running when the dispatcher
        # wires the eval-only job in — _eval_job is lock-guarded state
        # everywhere else (edl-lint EDL001)
        with self._lock:
            self._eval_job = EvaluationJob(
                self._eval_metrics_fn(), -1, num_task
            )

    def add_evaluation_task(
        self, is_time_based_eval, model_version=None
    ):
        if is_time_based_eval and self._task_d.finished():
            return
        if not model_version:
            model_version = self._master_servicer.get_model_version()
        with self._lock:
            # check-and-set under the lock: concurrent report_version RPCs
            # for the same version must not enqueue duplicate eval jobs
            if model_version == self._last_eval_checkpoint_version:
                return
            self._eval_checkpoint_versions.append(model_version)
            self._last_eval_checkpoint_version = model_version
        self.try_to_create_new_job()

    def try_to_create_new_job(self):
        with self._lock:
            if self._eval_job is None and self._eval_checkpoint_versions:
                version = self._eval_checkpoint_versions.pop(0)
                # the task count comes from create_tasks' return value, not
                # from re-reading the live queue (workers may already be
                # popping it concurrently)
                task_count = self._task_d.create_tasks(
                    TaskType.EVALUATION, version
                )
                self._eval_job = EvaluationJob(
                    self._eval_metrics_fn(), version, task_count
                )
                return True
        return False

    def add_evaluation_task_if_needed(self, model_version):
        """Step-based trigger (reference :184-199)."""
        if not model_version:
            model_version = self._master_servicer.get_model_version()
        if (
            self._eval_steps
            and model_version % self._eval_steps == 0
            and model_version > self._last_eval_checkpoint_version
        ):
            self.add_evaluation_task(
                is_time_based_eval=False, model_version=model_version
            )

    def report_evaluation_metrics(self, model_outputs, labels):
        with self._lock:
            if self._eval_job is None:
                return False
            return self._eval_job.report_evaluation_metrics(
                model_outputs, labels
            )

    def complete_task(self):
        with self._lock:
            if self._eval_job is None:
                return None
            self._eval_job.complete_task()
            if not self._eval_job.finished():
                return None
            metrics = self._eval_job.get_evaluation_summary()
            version = self._eval_job.model_version
            self.completed_job_metrics.append((version, metrics))
            if not self._eval_only:
                self._eval_job = None
        if self._metrics_writer and metrics:
            self._metrics_writer.write_dict_to_summary(
                metrics, version=version
            )
        logger.info("Evaluation metrics[v=%d]: %s", version, metrics)
        self.try_to_create_new_job()
        return metrics
