"""Write-ahead journal + compacted snapshot for the master's dispatcher
state.

The reference keeps todo/doing queues, epoch counters, and retry counts
purely in memory (master/task_dispatcher.py) — a master crash loses the
job's progress accounting even though every worker is still healthy.
This store makes the dispatcher's task-lifecycle state durable:

* every transition (tasks created, dispatched, done, failed, epoch
  rollover, retry count bump, model version) is appended to
  ``journal.jsonl`` in ``--job_state_dir`` BEFORE the in-memory state
  changes are observable (write-ahead),
* a compacted ``snapshot.json`` is written atomically (tmp + rename)
  every ``snapshot_every`` journal appends and the journal truncated,
  bounding replay time,
* a ``JOB_COMPLETE`` marker records that the job finished, so a
  relaunched master (or supervisor) does not redo a completed job,
* a ``restarts`` file counts recoveries — exported as the
  master_restarts gauge.

Crash model: SIGKILL of the master PROCESS (pod eviction, OOM-kill,
drills). Appends are flushed to the OS on every write, which survives
process death; pass fsync=True to also survive host power loss.

The journal line format is owned by TaskDispatcher (snapshot()/
restore()); this module only handles durability, atomicity, and replay
tolerance (a torn final line from a crash mid-append is skipped).
"""

import json
import os
import tempfile

from elasticdl_tpu.common.log_utils import default_logger as logger

JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_FILE = "snapshot.json"
COMPLETE_MARKER = "JOB_COMPLETE"
RESTARTS_FILE = "restarts"


class JobStateStore(object):
    def __init__(self, job_state_dir, snapshot_every=200, fsync=False):
        self._dir = job_state_dir
        self.snapshot_every = max(1, int(
            os.environ.get("EDL_STATE_SNAPSHOT_EVERY", snapshot_every)
        ))
        self._fsync = fsync
        os.makedirs(job_state_dir, exist_ok=True)
        self._journal_path = os.path.join(job_state_dir, JOURNAL_FILE)
        self._snapshot_path = os.path.join(job_state_dir, SNAPSHOT_FILE)
        self._had_state = (
            os.path.exists(self._journal_path)
            or os.path.exists(self._snapshot_path)
        )
        self._journal = None
        self._appends_since_snapshot = 0
        self.journal_appends = 0
        self.compactions = 0
        self.torn_lines = 0
        if self._had_state:
            self._bump_restarts()

    # ------------------------------------------------------------ loading

    def has_state(self):
        return self._had_state

    def load(self):
        """(snapshot dict or None, [journal events]). Tolerates a torn
        final journal line — the one write a SIGKILL can interrupt —
        whether it is a JSON prefix, non-UTF-8 block garbage, or
        missing its newline entirely; every dropped tail bumps the
        ``torn_lines`` counter. Corruption anywhere EARLIER in the
        journal still raises: that is data loss, not a crash artifact."""
        snapshot = None
        if os.path.exists(self._snapshot_path):
            with open(self._snapshot_path) as f:
                snapshot = json.load(f)
        events = []
        if os.path.exists(self._journal_path):
            self._trim_torn_tail()
            # binary read: a torn tail of raw block garbage must not
            # blow up the WHOLE read with UnicodeDecodeError before
            # per-line tolerance gets a chance
            with open(self._journal_path, "rb") as f:
                lines = f.readlines()
            for i, raw in enumerate(lines):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    events.append(json.loads(raw.decode("utf-8")))
                except ValueError:  # includes UnicodeDecodeError
                    if i == len(lines) - 1:
                        self.torn_lines += 1
                        logger.warning(
                            "Dropping torn final journal line (%d bytes)",
                            len(raw),
                        )
                    else:
                        raise
        return snapshot, events

    # ------------------------------------------------------------ writing

    def _trim_torn_tail(self):
        """Physically drop a newline-less journal tail. Without the
        trim, the next append would concatenate onto the torn line,
        promoting recoverable TAIL garbage into a corrupt mid-file
        line that load() rightly refuses to skip."""
        try:
            size = os.path.getsize(self._journal_path)
        except OSError:
            return
        if size == 0:
            return
        with open(self._journal_path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return
            f.seek(0)
            keep = f.read().rfind(b"\n") + 1  # 0: no newline at all
            f.truncate(keep)
        self.torn_lines += 1
        logger.warning(
            "Trimmed torn journal tail (%d bytes) before append",
            size - keep,
        )

    def _open_journal(self):
        if self._journal is None:
            self._trim_torn_tail()
            self._journal = open(self._journal_path, "a")
        return self._journal

    def append(self, event):
        """Write-ahead one lifecycle event. Returns True when the caller
        should compact (hand back a snapshot via write_snapshot)."""
        f = self._open_journal()
        f.write(json.dumps(event, separators=(",", ":")) + "\n")
        f.flush()
        if self._fsync:
            os.fsync(f.fileno())
        self.journal_appends += 1
        self._appends_since_snapshot += 1
        return self._appends_since_snapshot >= self.snapshot_every

    def write_snapshot(self, state):
        """Atomically persist the full state and truncate the journal —
        snapshot first, truncate after, so a crash between the two
        replays the journal against the NEW snapshot (events are
        idempotent under replay: dispatch of an absent task and done of
        an unknown id are no-ops)."""
        fd, tmp = tempfile.mkstemp(
            dir=self._dir, prefix=".snapshot."
        )
        with os.fdopen(fd, "w") as f:
            json.dump(state, f)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        if self._journal is not None:
            self._journal.close()
            self._journal = None
        open(self._journal_path, "w").close()
        self._appends_since_snapshot = 0
        self.compactions += 1

    def close(self):
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    # ------------------------------------------------- completion marker

    def mark_job_complete(self):
        path = os.path.join(self._dir, COMPLETE_MARKER)
        with open(path, "w") as f:
            f.write("complete\n")

    def is_job_complete(self):
        return os.path.exists(os.path.join(self._dir, COMPLETE_MARKER))

    # ------------------------------------------------- restart counting

    def _bump_restarts(self):
        path = os.path.join(self._dir, RESTARTS_FILE)
        try:
            with open(path) as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n = 0
        with open(path, "w") as f:
            f.write("%d\n" % (n + 1))

    @property
    def restart_count(self):
        """How many times a master has come up over existing state."""
        path = os.path.join(self._dir, RESTARTS_FILE)
        try:
            with open(path) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0
