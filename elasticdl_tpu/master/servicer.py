"""Master gRPC servicer (reference: master/servicer.py:24-137).

Implements the Master service over the hand-rolled binding
(proto/service.py). The WAIT protocol is preserved: when the todo queue is
empty but may refill (doing tasks could fail and re-queue, or a deferred
train-end callback is pending), workers are told to wait instead of exiting.
"""

import statistics
import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.master.task_dispatcher import TaskType
from elasticdl_tpu.observability.tracing import recorder
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto.convert import TASK_TYPE_TO_PB as _TASK_TYPE_TO_PB


class MasterServicer(object):
    def __init__(self, minibatch_size, task_d, evaluation_service=None,
                 tensorboard_service=None):
        self._task_d = task_d
        self._lock = threading.Lock()
        self._minibatch_size = minibatch_size
        # a restored dispatcher carries the pre-crash model version, so
        # step-based eval triggers don't re-fire for old versions
        self._version = getattr(task_d, "model_version", 0) or 0
        self._evaluation_service = evaluation_service
        self._tensorboard_service = tensorboard_service
        self._task_complete_times = {
            TaskType.TRAINING: [],
            TaskType.EVALUATION: [],
        }
        self._worker_liveness_time = {}
        self._workers = {}
        self._cluster_version = 0
        # per-worker tier-gauge step counters: gauges are written at a
        # monotonically increasing per-worker report index, never at
        # the model version — multiple reports between version bumps
        # would otherwise emit duplicate TB points at one step
        # (sawtooth/overwrite on some backends), and keeping only one
        # report per version would drop the tail of the cumulative
        # counters. Guarded by self._lock (gRPC thread pool).
        self._tier_gauge_steps = {}
        # training-plane tracing: one `task_dispatch` span per
        # outstanding dispatched task, opened at get_task and sealed
        # at report_task_result — the Task proto carries (trace_id,
        # span_id) so the worker's task span parents under it and the
        # whole dispatch->fetch->report hop merges into one tree keyed
        # by task id. Guarded by self._lock (gRPC thread pool).
        self._task_spans = {}
        if evaluation_service:
            evaluation_service.set_master_servicer(self)

    def get_model_version(self):
        return self._version

    # ------------------------------------------------------------- RPCs

    def get_task(self, request, _context=None):
        res = pb.Task(type=pb.NONE)
        res.model_version = self._version
        res.minibatch_size = self._minibatch_size
        if request.task_type == pb.EVALUATION:
            task_id, task = self._task_d.get_eval_task(request.worker_id)
        else:
            task_id, task = self._task_d.get(request.worker_id)

        if task:
            res.task_id = task_id
            res.shard_name = task.shard_name
            res.start = task.start
            res.end = task.end
            res.type = _TASK_TYPE_TO_PB[task.type]
            for k, v in task.extended_config.items():
                res.extended_config[k] = str(v)
            if task.type == TaskType.EVALUATION:
                # eval tasks pin the model version they evaluate
                res.model_version = task.model_version
            span = recorder().start_span(
                "task_dispatch", task_id=task_id,
                worker_id=request.worker_id, type=str(task.type),
            )
            res.trace_id = span.trace_id
            res.span_id = span.span_id
            with self._lock:
                # the same task re-dispatched (worker died, task
                # requeued) seals the previous span so every dispatch
                # attempt stays visible as its own span
                old = self._task_spans.pop(task_id, None)
                self._task_spans[task_id] = span
            if old is not None:
                old.event("redispatched", worker_id=request.worker_id)
                old.finish("redispatched")
        elif (not self._task_d.finished()) or (
            self._task_d.invoke_deferred_callback()
        ):
            res.type = pb.WAIT
        else:
            # the EXPLICIT end-of-job signal: workers may only exit on
            # this, never on a transport error (a transient master
            # outage is indistinguishable from shutdown on the wire)
            res.reason = pb.JOB_COMPLETE
        with self._lock:
            self._worker_liveness_time[request.worker_id] = time.time()
        return res

    def report_task_result(self, request, _context=None):
        self._finish_task_span(request.task_id,
                               ok=not request.err_message)
        if request.err_message:
            logger.warning(
                "Worker reported error: %s", request.err_message
            )
            _, _, worker_id = self._task_d.report(
                request.task_id, False,
                exec_counters=dict(request.exec_counters),
            )
        else:
            complete_time, task, worker_id = self._task_d.report(
                request.task_id, True,
                exec_counters=dict(request.exec_counters),
            )
            if task:
                with self._lock:
                    self._worker_liveness_time[worker_id] = time.time()
                    if task.type in self._task_complete_times:
                        self._task_complete_times[task.type].append(
                            complete_time
                        )
        self._write_tier_gauges(dict(request.exec_counters), worker_id)
        return pb.Empty()

    def _finish_task_span(self, task_id, ok):
        """Seal the dispatch span a report closes. A late duplicate
        report (requeued straggler) finds no span — its re-dispatch
        already sealed the old one — and is simply untraced."""
        with self._lock:
            span = self._task_spans.pop(task_id, None)
        if span is not None:
            span.event("reported", ok=ok)
            span.finish("ok" if ok else "error")

    def _write_tier_gauges(self, exec_counters, worker_id):
        """Workers piggyback cumulative tier-health counters (host-tier
        dropped row updates / failed cycles) on task reports as tier/
        keys, and RPC-resilience counters (rpc_retries, reconnects) as
        fault/ keys; write them through the TensorBoard service as
        gauges at a per-worker report index (reference analogue: the PS
        exposed parameters.debug_info — here the degradation signal
        rides the existing report RPC instead of a debug endpoint).
        Tags are per-worker (the counters are per-trainer cumulatives,
        so different workers' values must not interleave on one
        scalar); the dispatcher supplies the reporting worker's id. A
        report whose task is unknown (late duplicate from a requeued
        straggler) has no worker identity — dropped, since writing it
        to a bare tag would recreate the interleaving."""
        if not self._tensorboard_service or worker_id < 0:
            return
        suffix = "/worker-%d" % worker_id
        gauges = {
            k + suffix: v for k, v in exec_counters.items()
            if k.startswith(("tier/", "fault/"))
        }
        if gauges:
            # distinct step per report (see _tier_gauge_steps): every
            # cumulative value lands, steps strictly increase per tag
            with self._lock:
                step = self._tier_gauge_steps.get(worker_id, 0)
                self._tier_gauge_steps[worker_id] = step + 1
            self._tensorboard_service.write_dict_to_summary(
                gauges, version=step
            )

    def report_evaluation_metrics(self, request, _context=None):
        with self._lock:
            self._worker_liveness_time[request.worker_id] = time.time()
        if self._evaluation_service:
            self._evaluation_service.report_evaluation_metrics(
                request.model_outputs, request.labels
            )
        return pb.Empty()

    def report_version(self, request, _context=None):
        self._version = max(self._version, request.model_version)
        if hasattr(self._task_d, "record_model_version"):
            self._task_d.record_model_version(request.model_version)
        if self._evaluation_service:
            self._evaluation_service.add_evaluation_task_if_needed(
                model_version=request.model_version
            )
        return pb.Empty()

    def register_worker(self, request, _context=None):
        with self._lock:
            self._workers[request.worker_id] = {
                "address": request.address,
                "num_devices": request.num_devices,
                "registered_at": time.time(),
            }
            self._cluster_version += 1
            self._worker_liveness_time[request.worker_id] = time.time()
            # capture the version this registration produced while
            # still under the lock: a concurrent registration bumping
            # the counter between release and response would hand two
            # workers the same (newer) version and break the
            # version-change detection re-registration relies on
            cluster_version = self._cluster_version
        logger.info(
            "Worker %d registered from %s (%d devices)",
            request.worker_id, request.address, request.num_devices,
        )
        return pb.RegisterWorkerResponse(
            cluster_version=cluster_version
        )

    # --------------------------------------------------- watchdog helpers

    def get_average_task_complete_time(self):
        """Per-type average, defaulting to 300 s until 20 samples exist
        (fixes the reference's servicer.py:119-127, which compared the dict
        length — always 2 — against 20 and so never left the default)."""
        out = {}
        # snapshot under the lock: report_task_result appends from gRPC
        # threads while the watchdog thread averages (edl-lint EDL002)
        with self._lock:
            complete_times = {
                t: list(v) for t, v in self._task_complete_times.items()
            }
        for task_type, times in complete_times.items():
            if len(times) < 20:
                out[task_type] = 300.0
            else:
                out[task_type] = statistics.mean(times[-200:])
        return out

    def get_worker_liveness_time(self, worker_id):
        with self._lock:
            return self._worker_liveness_time.get(worker_id)
