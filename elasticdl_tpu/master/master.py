"""Master orchestrator: builds the dispatcher, gRPC service, evaluation
service, and (when instance management is configured) the worker fleet;
runs the wait loop with the straggler watchdog.

Parity with the reference's master/master.py:95-558, minus what the PS
deletion removes (PS pod management, PS command lines). Instance management
is pluggable via the duck-typed `instance_manager` argument
(start_workers / all_workers_failed / remove_worker / stop); backend
implementations (local-process and gated Kubernetes) live in
master/instance_manager.py once the elasticity milestone lands.
"""

import threading
import time
from concurrent import futures

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher, TaskType
from elasticdl_tpu.proto.service import (
    add_master_servicer_to_server,
    build_server,
)


class Master(object):
    def __init__(
        self,
        model_spec,
        training_data=None,
        validation_data=None,
        prediction_data=None,
        minibatch_size=32,
        records_per_task=256,
        num_epochs=1,
        evaluation_steps=0,
        eval_start_delay_secs=0,
        eval_throttle_secs=0,
        port=0,
        create_data_reader_fn=None,
        instance_manager=None,
        task_timeout_check_interval=30,
        callbacks_list=None,
        export_saved_model=False,
        tensorboard_service=None,
        checkpoint_dir_for_init=None,
        job_state_dir=None,
        fault_injector=None,
        shutdown_linger_secs=2.0,
    ):
        from elasticdl_tpu.data.reader.data_reader_factory import (
            create_data_reader,
        )

        self.spec = model_spec
        self.minibatch_size = minibatch_size
        create_fn = create_data_reader_fn or create_data_reader

        def shards_of(data):
            if not data:
                return {}
            return create_fn(data, records_per_task).create_shards()

        # crash recovery: with --job_state_dir the dispatcher journals
        # every task transition and a relaunched master restores
        # todo ∪ requeued-doing exactly (master/state_store.py)
        self.state_store = None
        if job_state_dir:
            from elasticdl_tpu.master.state_store import JobStateStore

            self.state_store = JobStateStore(job_state_dir)
            if self.state_store.has_state():
                logger.info(
                    "Recovering master state from %s (restart #%d)",
                    job_state_dir, self.state_store.restart_count,
                )

        self.task_d = TaskDispatcher(
            shards_of(training_data),
            shards_of(validation_data),
            shards_of(prediction_data),
            records_per_task,
            num_epochs,
            callbacks_list=callbacks_list,
            state_store=self.state_store,
        )
        self._fault_injector = fault_injector
        self._shutdown_linger_secs = shutdown_linger_secs
        if export_saved_model and training_data:
            self.task_d.add_deferred_callback_create_train_end_task()
        # wire master-side callbacks that act on the dispatcher
        # (MaxStepsStopping flips its stop_training flag on_task_end)
        if callbacks_list is not None:
            for cb in callbacks_list.callbacks:
                if hasattr(cb, "set_task_dispatcher"):
                    cb.set_task_dispatcher(self.task_d)
        # resume: validate the init checkpoint up front (fail fast at the
        # master, not minutes later in a worker's restore) and seed
        # step-counting callbacks with its version so max_steps counts
        # TOTAL job steps (reference _set_completed_steps_by_checkpoint,
        # master.py:176-192)
        if checkpoint_dir_for_init:
            from elasticdl_tpu.checkpoint import (
                get_latest_checkpoint_version,
            )

            version = get_latest_checkpoint_version(checkpoint_dir_for_init)
            if version < 0:
                raise ValueError(
                    "Invalid checkpoint directory %r"
                    % checkpoint_dir_for_init
                )
            if callbacks_list is not None:
                for cb in callbacks_list.callbacks:
                    if hasattr(cb, "set_completed_steps"):
                        cb.set_completed_steps(version)

        eval_only = bool(validation_data) and not training_data
        self.tensorboard_service = tensorboard_service
        self.evaluation_service = None
        if validation_data:
            self.evaluation_service = EvaluationService(
                tensorboard_service,
                self.task_d,
                eval_start_delay_secs,
                eval_throttle_secs,
                evaluation_steps,
                eval_only,
                model_spec.eval_metrics_fn,
            )
            self.task_d.set_evaluation_service(self.evaluation_service)

        from elasticdl_tpu.common.fault_injection import (
            maybe_wrap_servicer,
        )

        self.servicer = maybe_wrap_servicer(
            MasterServicer(
                minibatch_size,
                self.task_d,
                evaluation_service=self.evaluation_service,
                tensorboard_service=tensorboard_service,
            ),
            injector=fault_injector,
        )
        self.instance_manager = instance_manager
        self._port = port
        self._server = None
        self.port = None
        self._task_timeout_check_interval = task_timeout_check_interval
        self._watchdog_stopper = threading.Event()

    # ------------------------------------------------------------ lifecycle

    def prepare(self):
        """Start gRPC service + eval trigger + workers (reference
        Master.prepare, master.py:202-233)."""
        server = build_server(futures.ThreadPoolExecutor(max_workers=64))
        add_master_servicer_to_server(self.servicer, server)
        self.port = server.add_insecure_port("[::]:%d" % self._port)
        server.start()
        self._server = server
        logger.info("Master gRPC server started on port %d", self.port)
        if self.evaluation_service:
            self.evaluation_service.start()
        if self.tensorboard_service:
            self.tensorboard_service.start()
        if self.instance_manager:
            self.instance_manager.start_workers()
        self._start_watchdog()
        self._write_recovery_gauges()

    def _write_recovery_gauges(self):
        """Export the crash-recovery counters through the existing
        TensorBoard gauge path: master/restarts and the tasks requeued
        from the pre-crash doing set."""
        if not (self.tensorboard_service and self.state_store):
            return
        restarts = self.state_store.restart_count
        self.tensorboard_service.write_dict_to_summary(
            {
                "master/restarts": restarts,
                "master/recovery_requeued_tasks":
                    self.task_d.requeued_on_recovery,
            },
            version=restarts,
        )

    def run(self, poll_interval=1.0):
        """Block until all tasks finish (reference Master.run,
        master.py:235-260)."""
        try:
            while not self.task_d.finished():
                if (
                    self.instance_manager
                    and self.instance_manager.all_workers_failed()
                ):
                    raise RuntimeError("All workers failed")
                time.sleep(poll_interval)
            # serve the deferred train-end callback task if any
            while True:
                if self.task_d.finished():
                    if not self.task_d.invoke_deferred_callback():
                        break
                time.sleep(poll_interval)
            if self.state_store:
                # durable completion marker: a relaunched master (or the
                # drill supervisor) must not redo a finished job
                self.state_store.mark_job_complete()
            # linger so polling workers observe the explicit JOB_COMPLETE
            # NONE task instead of racing the server teardown into their
            # reconnect-retry path
            if self._shutdown_linger_secs:
                time.sleep(self._shutdown_linger_secs)
        finally:
            self.stop()
        return 0

    def stop(self):
        self._watchdog_stopper.set()
        if self.evaluation_service:
            self.evaluation_service.stop()
        # after the eval service: late metrics must not reopen the writer
        if self.tensorboard_service:
            self.tensorboard_service.stop()
        if self.instance_manager:
            self.instance_manager.stop()
        if self._server:
            self._server.stop(grace=1.0)
            self._server = None
        if self.state_store:
            self.state_store.close()

    # ------------------------------------------------------------ watchdog

    def _start_watchdog(self):
        t = threading.Thread(
            target=self._check_timeout_tasks_loop, daemon=True
        )
        t.start()

    def _check_timeout_tasks_loop(self):
        """Straggler watchdog: a task running > 3x the average completion
        time gets recovered and its worker removed (reference
        master.py:536-558)."""
        while not self._watchdog_stopper.wait(
            self._task_timeout_check_interval
        ):
            self.check_timeout_tasks()

    def check_timeout_tasks(self):
        avg_time = self.servicer.get_average_task_complete_time()
        now = time.time()
        for task_id, (worker_id, task, start_time) in (
            self.task_d.doing_tasks().items()
        ):
            if task.type not in (TaskType.TRAINING, TaskType.EVALUATION):
                continue
            if now - start_time > 3 * avg_time.get(task.type, 300.0):
                logger.info(
                    "Task %d timed out on worker %s; recovering",
                    task_id, worker_id,
                )
                self.task_d.recover_tasks(worker_id)
                if self.instance_manager:
                    self.instance_manager.remove_worker(worker_id)
