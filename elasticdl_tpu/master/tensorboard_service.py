"""TensorBoard service on the master (reference
master/tensorboard_service.py:21-63): evaluation metrics become scalar
summaries keyed by model version; a `tensorboard` subprocess serves them
when the binary exists (gated — the TPU image may not ship it).

Summaries are written with the dependency-free event writer
(common/tb_events.py) instead of tf.summary."""

import shutil
import subprocess

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.tb_events import EventFileWriter


class TensorboardService(object):
    def __init__(self, tensorboard_log_dir, master_ip="", port=6006):
        self._log_dir = tensorboard_log_dir
        self._master_ip = master_ip
        self._port = port
        self._writer = None
        self._tb_process = None
        self._closed = False

    def _ensure_writer(self):
        if self._closed:
            return None
        if self._writer is None:
            self._writer = EventFileWriter(self._log_dir)
        return self._writer

    def write_dict_to_summary(self, dictionary, version):
        """Scalar per metric at step=version (reference
        write_dict_to_summary, tensorboard_service.py:41-49). Writes
        after stop() are dropped (a worker RPC can race shutdown)."""
        writer = self._ensure_writer()
        if writer is None:
            logger.debug("Dropping metrics after stop(): %s", dictionary)
            return
        for key, value in dictionary.items():
            try:
                writer.add_scalar(key, float(value), version)
            except (TypeError, ValueError):
                logger.warning(
                    "Skipping non-scalar metric %s=%r", key, value
                )

    def start(self):
        """Launch the tensorboard subprocess if it is installed
        (reference start, :51-60)."""
        if shutil.which("tensorboard") is None:
            logger.warning(
                "tensorboard binary not found; summaries are still "
                "written to %s", self._log_dir,
            )
            return False
        self._tb_process = subprocess.Popen(
            [
                "tensorboard",
                "--logdir", self._log_dir,
                "--port", str(self._port),
                "--bind_all",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        logger.info("TensorBoard serving %s on :%d",
                    self._log_dir, self._port)
        return True

    def is_active(self):
        return (
            self._tb_process is not None
            and self._tb_process.poll() is None
        )

    def stop(self):
        self._closed = True
        if self._writer:
            self._writer.close()
            self._writer = None
        if self.is_active():
            self._tb_process.terminate()
            self._tb_process = None
