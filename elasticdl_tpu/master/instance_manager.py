"""Instance manager: the elasticity core.

Parity with the reference's InstanceManager
(master/k8s_instance_manager.py:52-388) minus PS pods (no parameter
servers on TPU):

* launches the worker fleet (k8s pods or local subprocesses);
* reacts to lifecycle events: a worker that dies has its in-flight tasks
  recovered back to the todo queue (`task_d.recover_tasks`) and is
  relaunched with a NEW worker id (reference :369-378) up to
  `relaunch_on_worker_failure` times; exit code 137 that is NOT an OOM
  kill means preemption and relaunches without burning a retry
  (reference :310-338);
* `all_workers_failed` aborts the job from the master wait loop
  (reference master.py:242-245);
* fractional pod priority: "high=0.5" marks the first half of workers
  high-priority (reference `_parse_worker_pod_priority`).

The k8s watch stream and the local process-waiter thread both funnel
into the same `_handle_worker_exit` path, so elasticity semantics are
identical and unit-testable without a cluster (the reference tests mock
the same boundary — k8s_instance_manager_test.py).
"""

import subprocess
import sys
import threading

from elasticdl_tpu.common.k8s_client import (
    ELASTICDL_REPLICA_INDEX_KEY,
    ELASTICDL_REPLICA_TYPE_KEY,
)
from elasticdl_tpu.common.log_utils import default_logger as logger

_EXIT_PREEMPTED = 137  # SIGKILL: evicted/preempted unless reason=OOMKilled


def parse_worker_pod_priority(num_workers, priority_spec):
    """'high=0.5' → the first half of worker indices get priority 'high'
    (reference k8s_instance_manager.py `_parse_worker_pod_priority`)."""
    if not priority_spec:
        return {i: None for i in range(num_workers)}
    if "=" in priority_spec:
        name, _, frac = priority_spec.partition("=")
        frac = float(frac)
        n_high = int(num_workers * frac)
        return {
            i: (name if i < n_high else None)
            for i in range(num_workers)
        }
    return {i: priority_spec for i in range(num_workers)}


class _WorkerRecord(object):
    def __init__(self, worker_id, original_index):
        self.worker_id = worker_id
        self.original_index = original_index  # priority slot
        self.phase = "Pending"
        self.relaunch_count = 0


class InstanceManagerBase(object):
    """Shared elasticity state machine over an abstract launch/kill
    backend."""

    def __init__(
        self,
        task_d,
        num_workers,
        relaunch_on_worker_failure=3,
        disable_relaunch=False,
        fault_injector=None,
    ):
        from elasticdl_tpu.common.fault_injection import FaultInjector

        self._task_d = task_d
        self._num_workers = num_workers
        self._max_relaunch = (
            0 if disable_relaunch else relaunch_on_worker_failure
        )
        self._lock = threading.Lock()
        self._workers = {}  # worker_id -> _WorkerRecord
        self._next_worker_id = 0
        self._stopping = False
        # chaos hooks for drill tests: EDL_FAULT_SPEC rules named
        # worker_launch / worker_exit fire here (delay a relaunch, kill
        # the master mid-launch, ...)
        self._fault_injector = (
            fault_injector or FaultInjector.from_env()
        )

    # backend hooks ------------------------------------------------------

    def _launch(self, worker_id, original_index):
        raise NotImplementedError

    def _kill(self, worker_id):
        raise NotImplementedError

    # public API used by Master ------------------------------------------

    def start_workers(self):
        for i in range(self._num_workers):
            self._start_worker(i)

    def _start_worker(self, original_index, relaunch_count=0):
        with self._lock:
            worker_id = self._next_worker_id
            self._next_worker_id += 1
            record = _WorkerRecord(worker_id, original_index)
            record.relaunch_count = relaunch_count
            self._workers[worker_id] = record
        logger.info(
            "Starting worker %d (slot %d)", worker_id, original_index
        )
        if self._fault_injector is not None:
            self._fault_injector.intercept("worker_launch")
        self._launch(worker_id, original_index)
        return worker_id

    def remove_worker(self, worker_id):
        """Kill a straggler (watchdog path, reference master.py:552-556).
        The resulting exit event relaunches it like any failure."""
        logger.info("Removing worker %d", worker_id)
        self._kill(worker_id)

    def all_workers_failed(self):
        with self._lock:
            if not self._workers:
                return False
            return all(
                r.phase in ("Failed", "Deleted")
                for r in self._workers.values()
            )

    def stop(self):
        with self._lock:
            self._stopping = True
            ids = list(self._workers)
        for worker_id in ids:
            try:
                self._kill(worker_id)
            except Exception:
                pass

    # event handling -----------------------------------------------------

    def _handle_worker_exit(
        self, worker_id, *, succeeded, exit_code=None, oom=False,
        deleted=False,
    ):
        """One dead worker: recover its tasks, decide on relaunch."""
        if self._fault_injector is not None:
            self._fault_injector.intercept("worker_exit")
        with self._lock:
            record = self._workers.get(worker_id)
            if self._stopping or record is None or record.phase in (
                "Succeeded", "Failed", "Deleted",
            ):
                return
            if succeeded:
                record.phase = "Succeeded"
                return
            record.phase = "Deleted" if deleted else "Failed"
            preempted = (
                exit_code == _EXIT_PREEMPTED and not oom
            ) or deleted
            relaunch = self._max_relaunch > 0 and (
                preempted or record.relaunch_count < self._max_relaunch
            )
            original_index = record.original_index
            relaunch_count = (
                record.relaunch_count
                if preempted
                else record.relaunch_count + 1
            )
        self._task_d.recover_tasks(worker_id)
        if relaunch:
            logger.info(
                "Relaunching worker %d (slot %d, relaunches used %d/%d%s)",
                worker_id, original_index, relaunch_count,
                self._max_relaunch,
                ", preempted" if preempted else "",
            )
            self._start_worker(
                original_index, relaunch_count=relaunch_count
            )
        else:
            logger.info("Worker %d will not be relaunched", worker_id)

    def worker_phase(self, worker_id):
        with self._lock:
            record = self._workers.get(worker_id)
            return record.phase if record else None


class K8sInstanceManager(InstanceManagerBase):
    """Workers are Kubernetes pods; events come from the watch stream."""

    def __init__(
        self,
        task_d,
        *,
        num_workers,
        worker_command,
        worker_args,
        k8s_client,
        resource_request=None,
        resource_limit=None,
        pod_priority="",
        restart_policy="Never",
        image_pull_policy="Always",
        envs=None,
        volume=None,
        relaunch_on_worker_failure=3,
        disable_relaunch=False,
    ):
        super().__init__(
            task_d,
            num_workers,
            relaunch_on_worker_failure=relaunch_on_worker_failure,
            disable_relaunch=disable_relaunch,
        )
        self._client = k8s_client
        self._image_pull_policy = image_pull_policy
        self._worker_command = list(worker_command)
        self._worker_args = list(worker_args)
        self._resource_request = resource_request or {}
        self._resource_limit = resource_limit or {}
        self._priorities = parse_worker_pod_priority(
            num_workers, pod_priority
        )
        self._restart_policy = restart_policy
        self._envs = envs or {}
        self._volume = volume

    def _launch(self, worker_id, original_index):
        self._client.create_worker_pod(
            worker_id,
            command=self._worker_command,
            args=self._worker_args + ["--worker_id", str(worker_id)],
            resource_requests=self._resource_request,
            resource_limits=self._resource_limit,
            priority_class=self._priorities.get(original_index),
            restart_policy=self._restart_policy,
            image_pull_policy=self._image_pull_policy,
            envs=self._envs,
            volume=self._volume,
        )

    def _kill(self, worker_id):
        self._client.delete_worker(worker_id)

    def stop(self):
        super().stop()
        self._client.stop()

    # ---- k8s event plumbing

    def event_cb(self, event):
        """Pod watch callback (reference `_event_cb`,
        k8s_instance_manager.py:284-384). Accepts kubernetes objects or
        plain dicts (tests)."""
        evt_type = _get(event, "type")
        pod = _get(event, "object")
        labels = _get(pod, "metadata", "labels") or {}
        if _get(labels, ELASTICDL_REPLICA_TYPE_KEY) != "worker":
            return
        worker_id = int(_get(labels, ELASTICDL_REPLICA_INDEX_KEY))
        phase = _get(pod, "status", "phase")
        if evt_type == "DELETED":
            self._handle_worker_exit(worker_id, succeeded=False,
                                     deleted=True)
            return
        if phase == "Succeeded":
            self._handle_worker_exit(worker_id, succeeded=True)
        elif phase == "Failed":
            exit_code, reason = _terminated_state(pod)
            self._handle_worker_exit(
                worker_id,
                succeeded=False,
                exit_code=exit_code,
                oom=(reason == "OOMKilled"),
            )


class LocalInstanceManager(InstanceManagerBase):
    """Workers are local subprocesses running
    `python -m elasticdl_tpu.worker.main` — the no-cluster elastic path
    (and the fault-injection surface the integration tests use)."""

    def __init__(
        self,
        task_d,
        *,
        num_workers,
        worker_args,
        relaunch_on_worker_failure=3,
        disable_relaunch=False,
        env=None,
    ):
        super().__init__(
            task_d,
            num_workers,
            relaunch_on_worker_failure=relaunch_on_worker_failure,
            disable_relaunch=disable_relaunch,
        )
        self._worker_args = list(worker_args)
        self._procs = {}
        self._env = env

    def _launch(self, worker_id, original_index):
        cmd = (
            [sys.executable, "-m", "elasticdl_tpu.worker.main"]
            + self._worker_args
            + ["--worker_id", str(worker_id)]
        )
        proc = subprocess.Popen(cmd, env=self._env)
        with self._lock:
            self._procs[worker_id] = proc
        threading.Thread(
            target=self._wait_proc,
            args=(worker_id, proc),
            daemon=True,
        ).start()

    def _wait_proc(self, worker_id, proc):
        code = proc.wait()
        if code == 0:
            self._handle_worker_exit(worker_id, succeeded=True)
        else:
            self._handle_worker_exit(
                worker_id,
                succeeded=False,
                exit_code=(
                    _EXIT_PREEMPTED if code == -9 else code
                ),
            )

    def _kill(self, worker_id):
        with self._lock:
            proc = self._procs.get(worker_id)
        if proc is not None and proc.poll() is None:
            proc.kill()


# ---------------------------------------------------------------- helpers


def _get(obj, *path):
    """Attribute/key access that works for kubernetes models and dicts."""
    for key in path:
        if obj is None:
            return None
        if isinstance(obj, dict):
            obj = obj.get(key)
        else:
            obj = getattr(obj, key, None)
    return obj


def _terminated_state(pod):
    """(exit_code, reason) of the first terminated container, if any."""
    statuses = _get(pod, "status", "container_statuses") or _get(
        pod, "status", "containerStatuses"
    )
    if not statuses:
        return None, None
    st = statuses[0]
    term = _get(st, "state", "terminated")
    if term is None:
        return None, None
    return _get(term, "exit_code") or _get(term, "exitCode"), _get(
        term, "reason"
    )
