"""Master process entrypoint (reference master/main.py:20-24 +
Master._create_instance_manager, master.py:387-534): parse flags, build
the Master with an instance manager, reconstruct worker command lines
from the parsed args, serve until the job finishes."""

import sys

from elasticdl_tpu.common.args import (
    MASTER_ONLY_ARGS,
    build_arguments_from_parsed_result,
    parse_master_args,
    parse_resource_spec,
)
from elasticdl_tpu.common import job_status
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.master.master import Master

def _infer_job_type(args):
    if args.prediction_data and not args.training_data:
        return "prediction_only"
    if args.validation_data and not args.training_data:
        return "evaluation_only"
    if args.validation_data:
        return "training_with_evaluation"
    return "training_only"


def build_worker_args(args, master_addr):
    worker_args = build_arguments_from_parsed_result(
        args, filter_args=MASTER_ONLY_ARGS
    )
    worker_args += [
        "--master_addr", master_addr,
        "--job_type", _infer_job_type(args),
    ]
    return worker_args


def create_instance_manager(args, task_d, master_port):
    """K8s pods when a worker image is configured, local subprocesses
    otherwise (the no-cluster path)."""
    if args.num_workers <= 0:
        return None
    if args.worker_image:
        from elasticdl_tpu.common.k8s_client import (
            Client,
            get_master_pod_name,
        )
        from elasticdl_tpu.master.instance_manager import (
            K8sInstanceManager,
        )

        # worker pods dial the master pod by its stable in-cluster name,
        # never localhost (that would be the worker's own netns)
        worker_args = build_worker_args(
            args,
            "%s:%d" % (get_master_pod_name(args.job_name), master_port),
        )
        manager_holder = {}

        def event_cb(event):
            manager = manager_holder.get("m")
            if manager is not None:
                manager.event_cb(event)

        client = Client(
            image_name=args.worker_image,
            namespace=args.namespace,
            job_name=args.job_name,
            event_callback=event_cb,
            cluster_spec=args.cluster_spec,
        )
        volume = None
        if args.volume:
            volume = parse_resource_spec(args.volume)
        manager = K8sInstanceManager(
            task_d,
            num_workers=args.num_workers,
            worker_command=["python", "-m", "elasticdl_tpu.worker.main"],
            worker_args=worker_args,
            k8s_client=client,
            resource_request=parse_resource_spec(
                args.worker_resource_request
            ),
            resource_limit=parse_resource_spec(args.worker_resource_limit),
            pod_priority=args.worker_pod_priority,
            restart_policy=args.restart_policy,
            image_pull_policy=args.image_pull_policy,
            volume=volume,
            relaunch_on_worker_failure=args.relaunch_on_worker_failure,
            disable_relaunch=args.disable_relaunch,
        )
        manager_holder["m"] = manager
        return manager
    from elasticdl_tpu.master.instance_manager import LocalInstanceManager

    return LocalInstanceManager(
        task_d,
        num_workers=args.num_workers,
        worker_args=build_worker_args(
            args, "localhost:%d" % master_port
        ),
        relaunch_on_worker_failure=args.relaunch_on_worker_failure,
        disable_relaunch=args.disable_relaunch,
    )


def main(argv=None):
    from elasticdl_tpu.common.platform_utils import (
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    # SIGUSR2 -> all-thread stack dump: a live wedged master can
    # always be interrogated without killing the job
    from elasticdl_tpu.observability.runtime_health import (
        install_sigusr2_dump,
    )

    install_sigusr2_dump()
    args = parse_master_args(argv)
    status_file = getattr(args, "job_status_file", "")
    job_status.write_job_status(status_file, job_status.PENDING)
    try:
        rc = _run_master(args, status_file)
    except BaseException:
        job_status.write_job_status(status_file, job_status.FAILED)
        raise
    job_status.write_job_status(
        status_file,
        job_status.SUCCEEDED if rc == 0 else job_status.FAILED,
    )
    return rc


def _validate_dataset_fn(spec, args):
    """Specs may omit dataset_fn only when the configured data reader
    derives one from its schema (model_utils.resolve_dataset_fn). Check
    at SUBMISSION time — the reader type is already known here — so a
    misconfiguration fails the master fast instead of crash-looping
    every worker on its first task."""
    if spec.dataset_fn is not None:
        return
    from elasticdl_tpu.common.model_utils import resolve_dataset_fn
    from elasticdl_tpu.data.reader.data_reader_factory import (
        build_data_reader,
    )

    data = (args.training_data or args.validation_data
            or args.prediction_data)
    reader = build_data_reader(
        data, args.records_per_task, args.data_reader_params,
        custom_data_reader=spec.custom_data_reader,
    )
    resolve_dataset_fn(spec, reader)


def _expose_tensorboard(instance_manager):
    """Cluster path only: publish the master's TensorBoard through a
    LoadBalancer service (reference k8s_tensorboard_client.py), waiting
    for the ingress IP on a daemon thread so master startup is not
    blocked."""
    import threading

    from elasticdl_tpu.common.k8s_tensorboard_client import (
        TensorBoardClient,
    )

    k8s_cli = getattr(instance_manager, "_client", None)
    if k8s_cli is None:
        return
    threading.Thread(
        target=lambda: TensorBoardClient(
            client=k8s_cli
        ).start_tensorboard_service(),
        daemon=True,
        name="tensorboard-exposure",
    ).start()


def _run_master(args, status_file=""):
    spec = get_model_spec(args.model_zoo, args.model_def)
    _validate_dataset_fn(spec, args)
    callbacks_list = None
    if spec.callbacks_fn is not None:
        from elasticdl_tpu.api.callbacks import CallbackList

        callbacks_list = CallbackList(spec.callbacks_fn())

    tensorboard_service = None
    if args.need_tensorboard:
        from elasticdl_tpu.master.tensorboard_service import (
            TensorboardService,
        )

        tensorboard_service = TensorboardService(
            args.tensorboard_log_dir or "/tmp/elasticdl_tb"
        )

    master = Master(
        spec,
        training_data=args.training_data or None,
        validation_data=args.validation_data or None,
        prediction_data=args.prediction_data or None,
        minibatch_size=args.minibatch_size,
        records_per_task=args.records_per_task,
        num_epochs=args.num_epochs,
        evaluation_steps=args.evaluation_steps,
        eval_start_delay_secs=args.eval_start_delay_secs,
        eval_throttle_secs=args.eval_throttle_secs,
        port=args.port,
        task_timeout_check_interval=args.task_timeout_check_interval,
        callbacks_list=callbacks_list,
        export_saved_model=args.export_saved_model,
        tensorboard_service=tensorboard_service,
        checkpoint_dir_for_init=args.checkpoint_dir_for_init,
        job_state_dir=args.job_state_dir or None,
    )
    if master.state_store and master.state_store.is_job_complete():
        # a relaunched master over a finished job: report success and
        # exit instead of re-serving an empty dispatcher
        logger.info("Job already complete per %s; nothing to do",
                    args.job_state_dir)
        return 0
    # gRPC port is bound in prepare(); the instance manager needs the
    # final address, so wire it afterwards.
    master.prepare()
    instance_manager = create_instance_manager(
        args, master.task_d, master.port
    )
    master.instance_manager = instance_manager
    if instance_manager:
        instance_manager.start_workers()
    if tensorboard_service is not None and args.worker_image:
        _expose_tensorboard(instance_manager)
    logger.info("Master ready on port %d", master.port)
    # name this process's span recorder; dispatch spans export to
    # $EDL_TRACE_DIR on exit (atexit) when tracing is armed
    from elasticdl_tpu.observability.tracing import configure

    configure(service="master:%d" % master.port)
    metrics = _start_metrics(args, master)
    job_status.write_job_status(status_file, job_status.RUNNING)
    try:
        return master.run()
    finally:
        if metrics is not None:
            metrics.close()


def _start_metrics(args, master):
    """The master's /metrics exposition (--metrics_port /
    EDL_METRICS_PORT, off by default): task-queue pressure, model
    version and the crash-recovery counters — the training-plane
    corner of the same scrape surface the serving fleet exposes."""
    from elasticdl_tpu.observability.metrics import (
        MetricsServer,
        counter_family,
        gauge_family,
        metrics_port_default,
    )

    port = (metrics_port_default() if args.metrics_port < 0
            else args.metrics_port)
    if port is None:
        return None

    def collect():
        todo, doing, eval_todo = master.task_d.queue_depths()
        restarts = (master.state_store.restart_count
                    if master.state_store else 0)
        return [
            gauge_family("edl_master_tasks_todo",
                         "training tasks queued", [({}, todo)]),
            gauge_family("edl_master_tasks_doing",
                         "training tasks dispatched and in flight",
                         [({}, doing)]),
            gauge_family("edl_master_eval_tasks_todo",
                         "evaluation tasks queued", [({}, eval_todo)]),
            gauge_family("edl_master_model_version",
                         "dispatcher model version",
                         [({}, master.task_d.model_version)]),
            counter_family("edl_master_restarts_total",
                           "master crash recoveries", restarts),
            counter_family(
                "edl_master_recovery_requeued_tasks_total",
                "doing-tasks requeued by journal recovery",
                master.task_d.requeued_on_recovery,
            ),
        ]

    server = MetricsServer(collect, port=port)
    logger.info("Master /metrics exposition on port %d", server.port)
    print("METRICS_READY port=%d" % server.port, flush=True)
    return server


if __name__ == "__main__":
    sys.exit(main())
