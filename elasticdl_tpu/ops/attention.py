"""Attention ops: naive reference, blockwise (memory-efficient), and a
Pallas flash-attention TPU kernel with a two-pass Pallas backward
(query-parallel dq, key-parallel dk/dv, P recomputed from the saved
logsumexp).

The reference framework has no attention/sequence stack at all
(SURVEY.md §5 "long-context: absent") — this is net-new TPU-first
capability: the single-chip kernels here are the local compute of the
ring/context-parallel attention in parallel/context_parallel.py, which
shards the sequence axis over the `sp` mesh axis.

Layout convention: [batch, heads, seq, head_dim].
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.ops.dispatch import interpret_mode, use_pallas

_NEG_INF = -1e30
NEG_INF = _NEG_INF  # masking constant shared with context_parallel


def softmax_merge(o, l, m, s, v_blk):
    """One online-softmax accumulation step: merge scores `s`
    [b,h,q,k_blk] and values `v_blk` [b,h,k_blk,d] into the running
    (output, denominator, rowmax) triple. Shared by blockwise_attention
    and ring attention so the subtle numerics live once."""
    m_new = jnp.maximum(m, s.max(-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
    return o_new, l_new, m_new


def softmax_finalize(o, l):
    return o / jnp.maximum(l, 1e-30)[..., None]


def naive_attention(q, k, v, causal=False, scale=None, window=None):
    """Reference softmax(q k^T) v; O(L^2) memory. The test oracle (the
    flash backward is the Pallas two-pass _flash_backward below).
    `window` (sliding-window/local attention): query at position p sees
    keys in (p - window, p] under causal, |p - k| < window otherwise —
    None means unbounded."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    lq, lk = scores.shape[-2], scores.shape[-1]
    q_pos = jnp.arange(lq)[:, None]
    k_pos = jnp.arange(lk)[None, :]
    mask = jnp.ones((lq, lk), bool)
    if causal:
        mask &= q_pos >= k_pos
    if window is not None:
        mask &= q_pos - k_pos < window
        if not causal:
            mask &= k_pos - q_pos < window
    scores = jnp.where(mask[None, None], scores, _NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def blockwise_attention(q, k, v, causal=False, scale=None, block_size=512,
                        window=None):
    """Online-softmax attention via lax.scan over key blocks: O(L) memory,
    differentiable, pure jnp (the fallback when the flash kernel can't
    run). Matches naive_attention to float tolerance."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, h, lq, d = q.shape
    lk = k.shape[2]
    _check_window(window, lq, lk)
    block = min(block_size, lk)
    if lk % block:
        # pad keys; padded positions masked below via k_pos >= lk
        pad = block - lk % block
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_blocks = k.shape[2] // block
    k_blocks = k.reshape(b, h, n_blocks, block, d)
    v_blocks = v.reshape(b, h, n_blocks, block, d)
    q_scaled = q * scale
    q_pos = jnp.arange(lq)

    def step(carry, inputs):
        o, l, m = carry
        kb, vb, kb_idx = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, kb)
        k_pos = kb_idx * block + jnp.arange(block)
        valid = jnp.broadcast_to((k_pos < lk)[None, :], (lq, block))
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        if window is not None:
            valid = valid & (q_pos[:, None] - k_pos[None, :] < window)
            if not causal:
                valid = valid & (k_pos[None, :] - q_pos[:, None] < window)
        s = jnp.where(valid[None, None], s, _NEG_INF)
        return softmax_merge(o, l, m, s, vb), None

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((b, h, lq), q.dtype)
    m0 = jnp.full((b, h, lq), _NEG_INF, q.dtype)
    (o, l, m), _ = jax.lax.scan(
        step,
        (o0, l0, m0),
        (
            jnp.moveaxis(k_blocks, 2, 0),
            jnp.moveaxis(v_blocks, 2, 0),
            jnp.arange(n_blocks),
        ),
    )
    return softmax_finalize(o, l)


def _check_window(window, lq, lk):
    """Sliding-window attention is defined for square self-attention
    only: with lq != lk a window can leave query rows with NO visible
    key, whose softmax is undefined (the jnp paths would emit mean(v),
    the kernel 0). Square shapes + window >= 1 guarantee the diagonal
    is always visible, so every row has at least one key."""
    if window is None:
        return
    if window < 1:
        raise ValueError("window must be >= 1, got %r" % (window,))
    if lq != lk:
        raise ValueError(
            "sliding-window attention requires square self-attention "
            "(lq == lk), got lq=%d lk=%d" % (lq, lk)
        )


def apply_rope(x, positions, theta=10000.0):
    """Rotary position embedding (RoPE) over the head dimension.

    x: [b, h, l, d]; positions: [l] int/float absolute positions.
    Rotates feature pairs (i, i+d/2) by positions * theta^(-2i/d), so
    q·k after rotation depends only on RELATIVE distance — the property
    that lets ring/Ulysses sequence shards use their global positions
    with no learned table. Math in fp32, result in x.dtype. An odd tail
    feature (d % 2) passes through unrotated.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, None]  # [1, 1, l, half]
    sin = jnp.sin(angles)[None, None]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:2 * half]
    rot = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    if d % 2:
        rot = jnp.concatenate([rot, xf[..., 2 * half:]], axis=-1)
    return rot.astype(x.dtype)


# --------------------------------------------------------- flash kernel


def _dims(contract_a, contract_b):
    return (((contract_a,), (contract_b,)), ((), ()))


def _block_run(qi, ki, block_q, block_k, causal, window):
    """Whether query block qi overlaps key block ki under the causal
    and/or sliding-window mask — the block-skip invariant shared by the
    forward and both backward kernels. Causal: some q position >= the
    block's first k position. Window: some k position inside the newest
    window of some q position (last k pos > first q pos - window)."""
    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k
    if window is not None:
        # newest k in block inside some q's lookback window
        back = ki * block_k + block_k - 1 > qi * block_q - window
        run = jnp.logical_and(run, back) if causal else back
        if not causal:
            # oldest k in block inside some q's lookahead window
            fwd = qi * block_q + block_q - 1 > ki * block_k - window
            run = jnp.logical_and(run, fwd)
    return run


def _block_mask(s, qi, ki, block_q, block_k, causal, window):
    if not causal and window is None:
        return s
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    keep = True
    if causal:
        keep = q_pos >= k_pos
    if window is not None:
        in_w = q_pos - k_pos < window
        keep = jnp.logical_and(keep, in_w) if causal else in_w
        if not causal:
            keep = jnp.logical_and(keep, k_pos - q_pos < window)
    return jnp.where(keep, s, _NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, scale, causal, window, block_q, block_k,
                  n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip key blocks fully outside the causal/window mask
    run = _block_run(qi, ki, block_q, block_k, causal, window)

    @pl.when(run)
    def _():
        q = q_ref[0] * scale
        s = jax.lax.dot_general(
            q, k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v_ref[0], dimension_numbers=_dims(1, 0),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(ki == n_k - 1)
    def _():
        l = l_scr[:]
        o_ref[0] = (
            acc_scr[:] / jnp.maximum(l, 1e-30)
        ).astype(o_ref.dtype)
        # logsumexp residual for the backward kernels: exp(s - lse) == P.
        # Defense in depth: a fully-skipped row (l == 0; unreachable for
        # the square shapes _check_window enforces) gets a +inf-class
        # sentinel so the backward's exp(-1e30 - lse) underflows to 0
        # instead of exploding.
        lse_ref[0] = jnp.where(
            l > 0.0,
            m_scr[:] + jnp.log(jnp.maximum(l, 1e-30)),
            -_NEG_INF,
        )


def _outer_spec(block, d):
    """Block indexed by grid dim 1 (the parallel/output dimension)."""
    return pl.BlockSpec(
        (1, block, d), lambda i, j, t: (i, j, 0),
        memory_space=pltpu.VMEM,
    )


def _inner_spec(block, d):
    """Block indexed by grid dim 2 (the sequential/streamed dimension)."""
    return pl.BlockSpec(
        (1, block, d), lambda i, j, t: (i, t, 0),
        memory_space=pltpu.VMEM,
    )



def _mosaic_params():
    """Grid semantics for all three flash kernels: (bh, output-block,
    streamed-block) = two parallel dims + one arbitrary (sequential
    accumulation over scratch). Lets Mosaic pipeline the parallel dims."""
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   window=None, with_residuals=False):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    n_q = lq // block_q
    n_k = lk // block_k
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        window=window,
        block_q=block_q,
        block_k=block_k,
        n_k=n_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            _outer_spec(block_q, d), _inner_spec(block_k, d),
            _inner_spec(block_k, d),
        ],
        out_specs=(
            _outer_spec(block_q, d),
            # lse rides as [bh, lq, 1] so stores stay (block_q, 1)
            # sublane columns — no 1-D reshape/transpose in the kernel
            _outer_spec(block_q, 1),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_mosaic_params(),
        interpret=interpret_mode() if interpret is None else interpret,
    )(q3, k3, v3)
    out = out.reshape(b, h, lq, d)
    if with_residuals:
        return out, lse.reshape(b, h, lq, 1)
    return out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, scale, causal, window,
                         block_q, block_k, n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _block_run(qi, ki, block_q, block_k, causal, window)

    @pl.when(run)
    def _():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        ) * scale
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window)
        p = jnp.exp(s - lse_ref[0])  # (block_q, block_k)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k_ref[0], dimension_numbers=_dims(1, 0),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          scale, causal, window, block_q, block_k, n_q):
    ki = pl.program_id(1)  # key block is the outer (parallel) dim here
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _block_run(qi, ki, block_q, block_k, causal, window)

    @pl.when(run)
    def _():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        ) * scale
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window)
        p = jnp.exp(s - lse_ref[0])  # (block_q, block_k)
        # dV_j += P^T dO ; dP = dO V^T ; dS = P*(dP - D) ; dK_j += dS^T Q
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do_ref[0], dimension_numbers=_dims(0, 0),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q_ref[0], dimension_numbers=_dims(0, 0),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                    block_k, interpret, window=None):
    """Two-pass flash backward: a dq kernel parallel over query blocks
    and a dk/dv kernel parallel over key blocks, both recomputing P from
    the saved logsumexp (the standard flash-attention backward; one
    matmul recompute instead of the O(L) blockwise-vjp scan)."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    interp = interpret_mode() if interpret is None else interpret
    n_q = lq // block_q
    n_k = lk // block_k
    # D_i = rowsum(dO * O), the softmax-jacobian diagonal term
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    do3 = g.reshape(bh, lq, d)
    lse3 = lse.reshape(bh, lq, 1)
    delta3 = delta.reshape(bh, lq, 1)

    col_q = _outer_spec(block_q, 1)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal,
            window=window, block_q=block_q, block_k=block_k, n_k=n_k,
        ),
        grid=(bh, n_q, n_k),
        in_specs=[
            _outer_spec(block_q, d), _inner_spec(block_k, d),
            _inner_spec(block_k, d), _outer_spec(block_q, d),
            col_q, col_q,
        ],
        out_specs=_outer_spec(block_q, d),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_mosaic_params(),
        interpret=interp,
    )(q3, k3, v3, do3, lse3, delta3)

    # key-block-parallel pass: q-side inputs stream over the inner dim
    col_q_t = _inner_spec(block_q, 1)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal,
            window=window, block_q=block_q, block_k=block_k, n_q=n_q,
        ),
        grid=(bh, n_k, n_q),
        in_specs=[
            _inner_spec(block_q, d), _outer_spec(block_k, d),
            _outer_spec(block_k, d), _inner_spec(block_q, d),
            col_q_t, col_q_t,
        ],
        out_specs=(_outer_spec(block_k, d), _outer_spec(block_k, d)),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_mosaic_params(),
        interpret=interp,
    )(q3, k3, v3, do3, lse3, delta3)
    return (
        dq.reshape(b, h, lq, d),
        dk.reshape(b, h, lk, d),
        dv.reshape(b, h, lk, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret, window):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret, window=window)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, window=window,
                              with_residuals=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, res,
               g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, interpret, window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=None, window=None):
    """Tiled online-softmax attention (Pallas). head_dim is zero-padded
    to the 128-lane width (zeros don't change q·k or add output columns
    that survive the final slice); falls back to blockwise_attention when
    Pallas is disabled or the sequence doesn't tile into the blocks.
    `window`: sliding-window/local attention (see naive_attention) — the
    block-skip predicate prunes out-of-window key blocks, so compute
    scales with window, not sequence."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    _check_window(window, lq, lk)
    tiles = (
        lq % block_q == 0 and lk % block_k == 0
        and block_q % 8 == 0 and block_k % 8 == 0
    )
    if not (use_pallas() and tiles):
        if use_pallas():
            logger.debug(
                "flash_attention falling back to blockwise: seq (%d, %d) "
                "does not tile into (%d, %d) blocks",
                lq, lk, block_q, block_k,
            )
        return blockwise_attention(q, k, v, causal=causal, scale=scale,
                                   window=window)
    if d % 128:
        pad = 128 - d % 128
        widths = ((0, 0), (0, 0), (0, 0), (0, pad))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    out = _flash(q, k, v, causal, scale, block_q, block_k, interpret,
                 window)
    return out[..., :d]
