"""Attention ops: naive reference, blockwise (memory-efficient), and a
Pallas flash-attention TPU kernel with a two-pass Pallas backward
(query-parallel dq, key-parallel dk/dv, P recomputed from the saved
logsumexp).

The reference framework has no attention/sequence stack at all
(SURVEY.md §5 "long-context: absent") — this is net-new TPU-first
capability: the single-chip kernels here are the local compute of the
ring/context-parallel attention in parallel/context_parallel.py, which
shards the sequence axis over the `sp` mesh axis.

Layout convention: [batch, heads, seq, head_dim].
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.ops.dispatch import interpret_mode, use_pallas

_NEG_INF = -1e30
NEG_INF = _NEG_INF  # masking constant shared with context_parallel


def softmax_merge(o, l, m, s, v_blk):
    """One online-softmax accumulation step: merge scores `s`
    [b,h,q,k_blk] and values `v_blk` [b,h,k_blk,d] into the running
    (output, denominator, rowmax) triple. Shared by blockwise_attention
    and ring attention so the subtle numerics live once."""
    m_new = jnp.maximum(m, s.max(-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
    return o_new, l_new, m_new


def softmax_finalize(o, l):
    return o / jnp.maximum(l, 1e-30)[..., None]


def naive_attention(q, k, v, causal=False, scale=None):
    """Reference softmax(q k^T) v; O(L^2) memory. The test oracle (the
    flash backward is the Pallas two-pass _flash_backward below)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = (
            jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        )
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def blockwise_attention(q, k, v, causal=False, scale=None, block_size=512):
    """Online-softmax attention via lax.scan over key blocks: O(L) memory,
    differentiable, pure jnp (the fallback when the flash kernel can't
    run). Matches naive_attention to float tolerance."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block = min(block_size, lk)
    if lk % block:
        # pad keys; padded positions masked below via k_pos >= lk
        pad = block - lk % block
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_blocks = k.shape[2] // block
    k_blocks = k.reshape(b, h, n_blocks, block, d)
    v_blocks = v.reshape(b, h, n_blocks, block, d)
    q_scaled = q * scale
    q_pos = jnp.arange(lq)

    def step(carry, inputs):
        o, l, m = carry
        kb, vb, kb_idx = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, kb)
        k_pos = kb_idx * block + jnp.arange(block)
        valid = k_pos < lk
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (lq, block))
        s = jnp.where(valid[None, None], s, _NEG_INF)
        return softmax_merge(o, l, m, s, vb), None

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((b, h, lq), q.dtype)
    m0 = jnp.full((b, h, lq), _NEG_INF, q.dtype)
    (o, l, m), _ = jax.lax.scan(
        step,
        (o0, l0, m0),
        (
            jnp.moveaxis(k_blocks, 2, 0),
            jnp.moveaxis(v_blocks, 2, 0),
            jnp.arange(n_blocks),
        ),
    )
    return softmax_finalize(o, l)


def apply_rope(x, positions, theta=10000.0):
    """Rotary position embedding (RoPE) over the head dimension.

    x: [b, h, l, d]; positions: [l] int/float absolute positions.
    Rotates feature pairs (i, i+d/2) by positions * theta^(-2i/d), so
    q·k after rotation depends only on RELATIVE distance — the property
    that lets ring/Ulysses sequence shards use their global positions
    with no learned table. Math in fp32, result in x.dtype. An odd tail
    feature (d % 2) passes through unrotated.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, None]  # [1, 1, l, half]
    sin = jnp.sin(angles)[None, None]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:2 * half]
    rot = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    if d % 2:
        rot = jnp.concatenate([rot, xf[..., 2 * half:]], axis=-1)
    return rot.astype(x.dtype)


# --------------------------------------------------------- flash kernel


def _dims(contract_a, contract_b):
    return (((contract_a,), (contract_b,)), ((), ()))


def _causal_run(qi, ki, block_q, block_k):
    """Whether query block qi overlaps key block ki under the causal
    mask (the block-skip invariant shared by forward and both backward
    kernels: any q position >= the block's first k position)."""
    return qi * block_q + block_q - 1 >= ki * block_k


def _causal_mask(s, qi, ki, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, scale, causal, block_q, block_k, n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip key blocks that lie entirely after this query block
    run = _causal_run(qi, ki, block_q, block_k) if causal else True

    @pl.when(run)
    def _():
        q = q_ref[0] * scale
        s = jax.lax.dot_general(
            q, k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v_ref[0], dimension_numbers=_dims(1, 0),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(ki == n_k - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # logsumexp residual for the backward kernels: exp(s - lse) == P
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _outer_spec(block, d):
    """Block indexed by grid dim 1 (the parallel/output dimension)."""
    return pl.BlockSpec(
        (1, block, d), lambda i, j, t: (i, j, 0),
        memory_space=pltpu.VMEM,
    )


def _inner_spec(block, d):
    """Block indexed by grid dim 2 (the sequential/streamed dimension)."""
    return pl.BlockSpec(
        (1, block, d), lambda i, j, t: (i, t, 0),
        memory_space=pltpu.VMEM,
    )



def _mosaic_params():
    """Grid semantics for all three flash kernels: (bh, output-block,
    streamed-block) = two parallel dims + one arbitrary (sequential
    accumulation over scratch). Lets Mosaic pipeline the parallel dims."""
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   with_residuals=False):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    n_q = lq // block_q
    n_k = lk // block_k
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        n_k=n_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            _outer_spec(block_q, d), _inner_spec(block_k, d),
            _inner_spec(block_k, d),
        ],
        out_specs=(
            _outer_spec(block_q, d),
            # lse rides as [bh, lq, 1] so stores stay (block_q, 1)
            # sublane columns — no 1-D reshape/transpose in the kernel
            _outer_spec(block_q, 1),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_mosaic_params(),
        interpret=interpret_mode() if interpret is None else interpret,
    )(q3, k3, v3)
    out = out.reshape(b, h, lq, d)
    if with_residuals:
        return out, lse.reshape(b, h, lq, 1)
    return out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, scale, causal, block_q,
                         block_k, n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _causal_run(qi, ki, block_q, block_k) if causal else True

    @pl.when(run)
    def _():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse_ref[0])  # (block_q, block_k)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k_ref[0], dimension_numbers=_dims(1, 0),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          scale, causal, block_q, block_k, n_q):
    ki = pl.program_id(1)  # key block is the outer (parallel) dim here
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _causal_run(qi, ki, block_q, block_k) if causal else True

    @pl.when(run)
    def _():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse_ref[0])  # (block_q, block_k)
        # dV_j += P^T dO ; dP = dO V^T ; dS = P*(dP - D) ; dK_j += dS^T Q
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do_ref[0], dimension_numbers=_dims(0, 0),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q_ref[0], dimension_numbers=_dims(0, 0),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                    block_k, interpret):
    """Two-pass flash backward: a dq kernel parallel over query blocks
    and a dk/dv kernel parallel over key blocks, both recomputing P from
    the saved logsumexp (the standard flash-attention backward; one
    matmul recompute instead of the O(L) blockwise-vjp scan)."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    interp = interpret_mode() if interpret is None else interpret
    n_q = lq // block_q
    n_k = lk // block_k
    # D_i = rowsum(dO * O), the softmax-jacobian diagonal term
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    do3 = g.reshape(bh, lq, d)
    lse3 = lse.reshape(bh, lq, 1)
    delta3 = delta.reshape(bh, lq, 1)

    col_q = _outer_spec(block_q, 1)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k,
        ),
        grid=(bh, n_q, n_k),
        in_specs=[
            _outer_spec(block_q, d), _inner_spec(block_k, d),
            _inner_spec(block_k, d), _outer_spec(block_q, d),
            col_q, col_q,
        ],
        out_specs=_outer_spec(block_q, d),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_mosaic_params(),
        interpret=interp,
    )(q3, k3, v3, do3, lse3, delta3)

    # key-block-parallel pass: q-side inputs stream over the inner dim
    col_q_t = _inner_spec(block_q, 1)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, n_q=n_q,
        ),
        grid=(bh, n_k, n_q),
        in_specs=[
            _inner_spec(block_q, d), _outer_spec(block_k, d),
            _outer_spec(block_k, d), _inner_spec(block_q, d),
            col_q_t, col_q_t,
        ],
        out_specs=(_outer_spec(block_k, d), _outer_spec(block_k, d)),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_mosaic_params(),
        interpret=interp,
    )(q3, k3, v3, do3, lse3, delta3)
    return (
        dq.reshape(b, h, lq, d),
        dk.reshape(b, h, lk, d),
        dv.reshape(b, h, lk, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, with_residuals=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=None):
    """Tiled online-softmax attention (Pallas). head_dim is zero-padded
    to the 128-lane width (zeros don't change q·k or add output columns
    that survive the final slice); falls back to blockwise_attention when
    Pallas is disabled or the sequence doesn't tile into the blocks."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    tiles = (
        lq % block_q == 0 and lk % block_k == 0
        and block_q % 8 == 0 and block_k % 8 == 0
    )
    if not (use_pallas() and tiles):
        if use_pallas():
            logger.debug(
                "flash_attention falling back to blockwise: seq (%d, %d) "
                "does not tile into (%d, %d) blocks",
                lq, lk, block_q, block_k,
            )
        return blockwise_attention(q, k, v, causal=causal, scale=scale)
    if d % 128:
        pad = 128 - d % 128
        widths = ((0, 0), (0, 0), (0, 0), (0, pad))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    out = _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return out[..., :d]
