"""Attention ops: naive reference, blockwise (memory-efficient), and a
Pallas flash-attention TPU kernel with a two-pass Pallas backward
(query-parallel dq, key-parallel dk/dv, P recomputed from the saved
logsumexp).

The reference framework has no attention/sequence stack at all
(SURVEY.md §5 "long-context: absent") — this is net-new TPU-first
capability: the single-chip kernels here are the local compute of the
ring/context-parallel attention in parallel/context_parallel.py, which
shards the sequence axis over the `sp` mesh axis.

Layout convention: [batch, heads, seq, head_dim].
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.ops.dispatch import (
    CompilerParams,
    interpret_mode,
    is_tpu_backend,
    use_cond_mask,
    use_paged_kernel,
    use_pallas,
)

_NEG_INF = -1e30
NEG_INF = _NEG_INF  # masking constant shared with context_parallel
# The kernels run their online softmax in the exp2 domain: log2(e) is
# folded into the (already present) q scale multiply, so every
# per-element exp() in the inner loop becomes the VPU-native exp2()
# without the implicit x*log2e multiply exp() performs. Outputs convert
# back to natural-log units (lse) at the block epilogue, so nothing
# outside the kernels sees base-2 values.
_LOG2E = float(np.log2(np.e))
_LN2 = float(np.log(2.0))

# Tuned flash block defaults: hardware sweeps (scripts/bench_attention.py
# via scripts/hw_session.py) persist their winner here so every call site
# that leaves block sizes unset — the model zoo, ring attention — picks
# it up. Resolution order: explicit argument > EDL_FLASH_BLOCK_Q/K env >
# ops/flash_tuning.json > 128.
_TUNING_FILE = os.path.join(os.path.dirname(__file__),
                            "flash_tuning.json")
_tuning_cache = None


def _tuned_blocks():
    global _tuning_cache
    if _tuning_cache is None:
        cfg = {}
        try:
            with open(_TUNING_FILE) as f:
                cfg = json.load(f)
        except (OSError, ValueError):
            pass
        _tuning_cache = cfg if isinstance(cfg, dict) else {}
    return _tuning_cache


def _align8(value):
    """Flash blocks must be multiples of 8 (_flash_tiles) — a misaligned
    tuned value would silently disable the kernel repo-wide, so round
    down instead."""
    return max(8, (int(value) // 8) * 8)


def resolve_block(explicit, which):
    """Resolve a flash block size: `which` is "q" or "k"."""
    if explicit is not None:
        return int(explicit)
    raw = os.environ.get("EDL_FLASH_BLOCK_%s" % which.upper(), "")
    if raw:
        try:
            return _align8(raw)
        except ValueError:
            pass
    value = _tuned_blocks().get("block_%s" % which)
    try:
        return _align8(value) if value else 128
    except (TypeError, ValueError):
        return 128


def resolve_paged_rows(explicit=None):
    """Query-row tile for the fused paged decode kernel
    (_paged_decode_fused): the group*t query rows of each (batch,
    kv-head) program are padded up to a multiple of this, so it is the
    kernel's sublane occupancy knob — bigger tiles round tiny
    verify-k/GQA row counts up to fuller VPU/MXU sublanes at the price
    of masked-row FLOPs. Resolution order mirrors the flash blocks:
    explicit argument > EDL_PAGED_ROWS env > flash_tuning.json
    "paged_rows" > 8. The default 8 is the CPU-SAFE floor (one f32
    sublane tile): interpret mode pays per-element for padding, and 8
    is also the smallest legal Mosaic row tile, so an untuned install
    is correct everywhere — scripts/bench_attention.py --paged sweeps
    and persists the hardware winner."""
    if explicit is not None:
        return _align8(explicit)
    raw = os.environ.get("EDL_PAGED_ROWS", "")
    if raw:
        try:
            return _align8(raw)
        except ValueError:
            pass
    value = _tuned_blocks().get("paged_rows")
    try:
        return _align8(value) if value else 8
    except (TypeError, ValueError):
        return 8


def softmax_merge(o, l, m, s, v_blk, w_scale=None):
    """One online-softmax accumulation step: merge scores `s`
    [b,h,q,k_blk] and values `v_blk` [b,h,k_blk,d] into the running
    (output, denominator, rowmax) triple. Shared by blockwise_attention,
    ring attention and the paged decode scan so the subtle numerics
    live once.

    `w_scale` [b,h,k_blk] (optional) multiplies the weights ONLY in the
    value matmul — the v-side of the deferred int8-KV dequantize:
    `p @ (v8 * vs) == (p * vs^T) @ v8`, so scaling the [*, k] weights
    (a head_dim-times smaller array than the rows) lets `v_blk` stay
    int8 all the way into the matmul operand read. The softmax
    denominator `l` is NOT scaled — it normalizes probabilities, which
    are dequantize-invariant."""
    m_new = jnp.maximum(m, s.max(-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(-1)
    pv = p if w_scale is None else p * w_scale[..., None, :]
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", pv,
                                             v_blk)
    return o_new, l_new, m_new


def softmax_finalize(o, l):
    return o / jnp.maximum(l, 1e-30)[..., None]


def lse_merge(o, lse, o_i, lse_i):
    """Merge two NORMALIZED attention partials (o, logsumexp) over the
    same queries but disjoint key sets — the combine step of ring
    attention (parallel/context_parallel.py). A fully-masked partial
    (lse_i == NEG_INF) contributes zero weight. Accumulate in float32."""
    lse_new = jnp.logaddexp(lse, lse_i)
    w = jnp.exp(lse - lse_new)[..., None]
    w_i = jnp.exp(lse_i - lse_new)[..., None]
    return o * w + o_i * w_i, lse_new


def group_size(q, k):
    """Grouped-query group size: q heads per kv head. 1 for standard
    multi-head attention; >1 when k/v carry fewer heads (GQA; ==num_heads
    for multi-query). Validates divisibility."""
    h, hkv = q.shape[1], k.shape[1]
    if h % hkv:
        raise ValueError(
            "grouped-query attention needs num_heads %% num_kv_heads "
            "== 0, got %d q heads / %d kv heads" % (h, hkv)
        )
    return h // hkv


def expand_kv(kv, num_heads):
    """Broadcast grouped-query K/V [b, hkv, l, d] to the full q head
    count (head j reads kv head j // group — the standard GQA layout:
    consecutive q heads share a kv head). Fallback for the jnp paths and
    kernels without native grouping; the Pallas flash kernels instead
    index kv blocks through the same j // group map, moving each kv
    block HBM->VMEM once per group instead of materializing the repeat."""
    hkv = kv.shape[1]
    if hkv == num_heads:
        return kv
    if num_heads % hkv:
        raise ValueError(
            "cannot expand %d kv heads to %d q heads" % (hkv, num_heads)
        )
    return jnp.repeat(kv, num_heads // hkv, axis=1)


def _check_segments(segments, b, lq, lk):
    """Normalize the sequence-packing mask argument.

    Accepted forms:
      * one [b, l] id array — square self-attention (q and k share the
        ids; every position sees itself, so no row is ever fully
        masked), or
      * a (q_seg [b, lq], k_seg [b, lk]) pair — rectangular, e.g. one
        ring-attention rotation where the held kv shard's ids differ
        from the local query shard's (rows CAN be fully masked there;
        the lse sentinel handling in attention_forward_lse covers it).

    Returns (q_seg, k_seg) int32 or None."""
    if segments is None:
        return None
    if isinstance(segments, (tuple, list)):
        if len(segments) != 2:
            raise ValueError(
                "segments pair must be (q_seg, k_seg), got %d items"
                % len(segments)
            )
        q_seg = jnp.asarray(segments[0], jnp.int32)
        k_seg = jnp.asarray(segments[1], jnp.int32)
    else:
        if lq != lk:
            raise ValueError(
                "a single segments array requires square self-"
                "attention (lq == lk), got lq=%d lk=%d; pass a "
                "(q_seg, k_seg) pair for rectangular shapes"
                % (lq, lk)
            )
        q_seg = k_seg = jnp.asarray(segments, jnp.int32)
    if q_seg.shape != (b, lq) or k_seg.shape != (b, lk):
        raise ValueError(
            "segments must be [batch, seq]: q side (%d, %d), k side "
            "(%d, %d); got %r / %r"
            % (b, lq, b, lk, tuple(q_seg.shape), tuple(k_seg.shape))
        )
    return q_seg, k_seg


def segments_float0(segments):
    """The float0 (empty) cotangent for integer segment ids — what a
    custom_vjp backward must return for a segments argument. Accepts
    None, one array, or the (q_seg, k_seg) pair."""
    if segments is None:
        return None
    if isinstance(segments, (tuple, list)):
        return tuple(
            np.zeros(s.shape, jax.dtypes.float0) for s in segments
        )
    return np.zeros(segments.shape, jax.dtypes.float0)


def naive_attention(q, k, v, causal=False, scale=None, window=None,
                    segments=None):
    """Reference softmax(q k^T) v; O(L^2) memory. The test oracle (the
    flash backward is the Pallas two-pass _flash_backward below).
    `window` (sliding-window/local attention): query at position p sees
    keys in (p - window, p] under causal, |p - k| < window otherwise —
    None means unbounded. k/v may carry fewer heads than q (GQA).
    `segments` [b, l] int: sequence-packing mask — attention stays
    within same-id runs (cross-segment scores are masked out)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    _check_window(window, q.shape[2], k.shape[2])
    segments = _check_segments(segments, q.shape[0], q.shape[2],
                               k.shape[2])
    k = expand_kv(k, q.shape[1])
    v = expand_kv(v, q.shape[1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    lq, lk = scores.shape[-2], scores.shape[-1]
    q_pos = jnp.arange(lq)[:, None]
    k_pos = jnp.arange(lk)[None, :]
    mask = jnp.ones((lq, lk), bool)
    if causal:
        mask &= q_pos >= k_pos
    if window is not None:
        mask &= q_pos - k_pos < window
        if not causal:
            mask &= k_pos - q_pos < window
    keep = jnp.broadcast_to(mask[None, None], scores.shape)
    if segments is not None:
        q_seg, k_seg = segments
        seg_mask = q_seg[:, :, None] == k_seg[:, None, :]
        keep = keep & seg_mask[:, None]
    scores = jnp.where(keep, scores, _NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def blockwise_attention(q, k, v, causal=False, scale=None, block_size=512,
                        window=None, with_lse=False, segments=None,
                        pos_offset=0):
    """Online-softmax attention via lax.scan over key blocks: O(L) memory,
    differentiable, pure jnp (the fallback when the flash kernel can't
    run). Matches naive_attention to float tolerance. With
    `with_lse=True` also returns the float32 logsumexp [b, h, lq] (the
    ring-attention partial form; see attention_forward_lse).
    `segments` [b, l] int: sequence-packing mask (see naive_attention)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, h, lq, d = q.shape
    lk = k.shape[2]
    _check_window(window, lq, lk)
    segments = _check_segments(segments, b, lq, lk)
    k = expand_kv(k, h)
    v = expand_kv(v, h)
    block = min(block_size, lk)
    q_seg = k_seg = None
    if segments is not None:
        q_seg, k_seg = segments
    if lk % block:
        # pad keys; padded positions masked below via k_pos >= lk
        pad = block - lk % block
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if k_seg is not None:
            k_seg = jnp.pad(k_seg, ((0, 0), (0, pad)),
                            constant_values=-1)
    n_blocks = k.shape[2] // block
    k_blocks = k.reshape(b, h, n_blocks, block, d)
    v_blocks = v.reshape(b, h, n_blocks, block, d)
    q_scaled = q * scale
    q_pos = jnp.arange(lq) + pos_offset

    def step(carry, inputs):
        o, l, m = carry
        kb, vb, kb_idx = inputs[:3]
        s = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, kb)
        k_pos = kb_idx * block + jnp.arange(block)
        valid = jnp.broadcast_to((k_pos < lk)[None, :], (lq, block))
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        if window is not None:
            valid = valid & (q_pos[:, None] - k_pos[None, :] < window)
            if not causal:
                valid = valid & (k_pos[None, :] - q_pos[:, None] < window)
        keep = jnp.broadcast_to(valid[None, None], s.shape)
        if segments is not None:
            seg_kb = inputs[3]  # [b, block]
            keep = keep & (
                q_seg[:, :, None] == seg_kb[:, None, :]
            )[:, None]
        s = jnp.where(keep, s, _NEG_INF)
        return softmax_merge(o, l, m, s, vb), None

    xs = [
        jnp.moveaxis(k_blocks, 2, 0),
        jnp.moveaxis(v_blocks, 2, 0),
        jnp.arange(n_blocks),
    ]
    if segments is not None:
        xs.append(
            jnp.moveaxis(k_seg.reshape(b, n_blocks, block), 1, 0)
        )
    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((b, h, lq), q.dtype)
    m0 = jnp.full((b, h, lq), _NEG_INF, q.dtype)
    (o, l, m), _ = jax.lax.scan(step, (o0, l0, m0), tuple(xs))
    out = softmax_finalize(o, l)
    if with_lse:
        lse = (m + jnp.log(jnp.maximum(l, 1e-30))).astype(jnp.float32)
        return out, lse
    return out


def _paged_valid(k_pos, bid, length, row_pos, window):
    """The ONE paged-decode visibility predicate, shared by the lax.scan
    oracle and the fused Pallas kernel so the two paths can never
    disagree about a mask bit (the flash kernels' _block_run/_block_mask
    discipline, applied to the paged shape). All operands broadcast:

      k_pos:   absolute position of a pool row (block j row r sits at
               j*block_size + r — the block table is position-ordered)
      bid:     the row's block id; -1 marks an unallocated table slot
               (the gather clamps to block 0, this predicate masks it)
      length:  tokens already cached; rows at k_pos >= length are junk
               (the partially-filled tail of the newest block)
      row_pos: the query row's absolute position (length + tile offset)
      window:  sliding window — a row sees keys k_pos > row_pos - window
               (static; None = unbounded)
    """
    valid = (k_pos < length) & (bid >= 0)
    if window is not None:
        valid = valid & (k_pos > row_pos - window)
    return valid


def _tile_causal_mask(group, t, window):
    """[group*t, t] visibility of the query tile's OWN keys, shared by
    the scan and fused paths (both merge the tile outside the pool
    stream): tile key j' (absolute position length + j') is visible to
    tile row j iff j' <= j — causal within the tile — and any window >= 1
    keeps the diagonal (_check_window)."""
    tile = jnp.arange(t)
    tri = tile[:, None] >= tile[None, :]  # [t_q, t_k] causal
    if window is not None:
        tri = tri & (tile[:, None] - tile[None, :] < window)
    return jnp.broadcast_to(
        tri[None, :, :], (group, t, t)
    ).reshape(group * t, t)


def paged_decode_attention(q, k_cur, v_cur, k_pool, v_pool, block_table,
                           length, scale=None, window=None,
                           k_scale_pool=None, v_scale_pool=None,
                           k_cur_scale=None, v_cur_scale=None,
                           use_kernel=None):
    """Decode attention over a BLOCK-PAGED KV pool for a tile of
    1 <= t new query tokens per sequence.

    The serving engine's paged pool (serving/kv_pool.py) stores every
    sequence's cached keys/values as fixed-size blocks scattered through
    one shared `[num_blocks, block_size, kv_heads, head_dim]` arena per
    layer; a sequence's logical cache is its BLOCK TABLE — the ordered
    block ids covering positions `[j*block_size, (j+1)*block_size)`.
    This op attends a sequence's query TILE over exactly that table,
    streaming one block at a time through the same online-softmax
    merge `blockwise_attention` scans with (softmax_merge /
    softmax_finalize), so no contiguous `seq_len` stripe is ever
    gathered or materialized: peak extra memory is ONE block per step.

    t = 1 is the classic per-token decode step. t > 1 is the
    VERIFY-k tile (speculative decode: the target checks k drafted
    tokens in one step) and the shared-prefix SUFFIX prefill (the
    unshared tail of a prompt decodes as one tile over the resident
    prefix blocks) — tile row j sits at absolute position
    `length + j`, sees every pool row `k_pos < length`, and sees tile
    keys `j' <= j` (causal within the tile).

    q:      [b, h, t, d]   the tile ([b, h, d] accepted for the t = 1
                           legacy shape; the result then drops t too)
    k_cur:  [b, hkv, t, d] the tile's own keys/values (at positions
    v_cur:  [b, hkv, t, d] `length + j`; NOT in the pool yet — the
                           engine scatters the committed rows after
                           the step)
    k_pool: [num_blocks, block_size, hkv, d]   shared arenas
    v_pool: [num_blocks, block_size, hkv, d]
    block_table: [b, m] int32, -1 padded past the allocated blocks
    length: [b] int32  tokens already cached (positions [0, length)
            are live; later rows of a partially-filled block are junk
            and masked, exactly like the dense decode's `k_pos <= pos`)
    window: sliding-window size (row j sees keys at
            `k_pos > length + j - window`).

    INT8 ARENAS (k_scale_pool is not None): the pools hold symmetric
    per-row int8 rows and the scale pools their f32 per-row scales
    `[num_blocks, block_size, hkv, 1]`; k_cur/v_cur are then int8 with
    `k_cur_scale`/`v_cur_scale` `[b, hkv, t, 1]` (the model quantizes
    the tile at the sow — quantize-at-insertion). The dequantize is
    DEFERRED into the blockwise online-softmax scan: k-scales fold
    into the per-block [*, block_size] score tile and v-scales into
    the weights (softmax_merge's w_scale), so no float copy of any
    cache row is ever materialized — the per-step dequantize work is
    on arrays head_dim-times smaller than the rows, and the dominant
    HBM stream (the arenas) stays int8 end to end. Same math as the
    offline dense int8 decode's deferral (transformer_lm._decode_step),
    reduction order aside.

    Table entries are traced values: block churn and sequence growth
    never recompile the consuming program. k/v may carry fewer heads
    than q (GQA): q heads are grouped under their kv head like the
    dense `_decode_step`, so pool reads scale with hkv. Returns
    [b, h, t, d] in float32 (the dense decode path's softmax
    precision).

    DISPATCH (`use_kernel`): None (default) auto-selects — the fused
    Pallas kernel (_paged_decode_fused) when dispatch.use_paged_kernel()
    says kernels are on AND _paged_kernel_supported() accepts the
    shapes; the lax.scan above otherwise. True/False (static) pin a
    path — the bench legs and the parity battery compare the two
    directly. Both paths share _paged_valid/_tile_causal_mask and the
    same outside-the-stream tile merge, so they can only differ by
    floating-point reduction order."""
    quantized = k_scale_pool is not None
    if quantized and (v_scale_pool is None or k_cur_scale is None
                      or v_cur_scale is None):
        raise ValueError(
            "int8 paged attention needs all four scale operands "
            "(k_scale_pool, v_scale_pool, k_cur_scale, v_cur_scale)"
        )
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, :, None, :]
        k_cur = k_cur[:, :, None, :]
        v_cur = v_cur[:, :, None, :]
        if quantized:
            k_cur_scale = k_cur_scale[:, :, None, :]
            v_cur_scale = v_cur_scale[:, :, None, :]
    b, h, t, d = q.shape
    hkv = k_cur.shape[1]
    if h % hkv:
        raise ValueError(
            "paged decode needs num_heads %% num_kv_heads == 0, got "
            "%d q heads / %d kv heads" % (h, hkv)
        )
    group = h // hkv
    block_size = k_pool.shape[1]
    m = block_table.shape[1]
    scale = scale if scale is not None else d ** -0.5
    f32 = jnp.float32
    # group layout [b, hkv, group, t, d] flattened to a (group*t) query
    # axis: kv head j serves q heads [j*group, (j+1)*group) — the dense
    # _decode_step's reshape — and softmax_merge's [b, h, q, k]
    # contract applies as-is with hkv as the head axis
    qg = (q * scale).reshape(b, hkv, group, t, d).astype(f32)
    qf = qg.reshape(b, hkv, group * t, d)
    length = jnp.asarray(length, jnp.int32)
    row_pos = length[:, None] + jnp.arange(t)[None, :]  # [b, t]

    def step(carry, j):
        o, l, mx = carry
        bid = block_table[:, j]  # [b]; -1 = unallocated
        safe = jnp.maximum(bid, 0)  # gather clamps; validity masks below
        kb = k_pool[safe].astype(f32)  # [b, block_size, hkv, d]
        vb = v_pool[safe].astype(f32)
        s = jnp.einsum("bhqd,bkhd->bhqk", qf, kb)  # [b, hkv, g*t, bs]
        w_scale = None
        if quantized:
            # deferred dequantize: the k-row scales multiply the
            # [*, block_size] score tile (head_dim-times smaller than
            # the rows), the v-row scales ride to softmax_merge's
            # weight multiply — the arenas stream int8, nothing floats
            ks = k_scale_pool[safe][..., 0]  # [b, block_size, hkv]
            s = s * ks.transpose(0, 2, 1)[:, :, None, :]
            w_scale = v_scale_pool[safe][..., 0].transpose(0, 2, 1)
        k_pos = j * block_size + jnp.arange(block_size)[None, None, :]
        valid = jnp.broadcast_to(
            _paged_valid(
                k_pos,                   # [1, 1, block_size]
                bid[:, None, None],      # [b, 1, 1]
                length[:, None, None],   # [b, 1, 1]
                row_pos[..., None],      # [b, t, 1]
                window,
            ),
            (b, t, block_size),
        )
        # [b, t, bs] -> [b, 1, group, t, bs] -> flatten the query axis
        vt = jnp.broadcast_to(
            valid[:, None, None], (b, hkv, group, t, block_size)
        ).reshape(b, hkv, group * t, block_size)
        s = jnp.where(vt, s, _NEG_INF)
        return softmax_merge(o, l, mx, s, vb.transpose(0, 2, 1, 3),
                             w_scale=w_scale), None

    if use_kernel is None:
        use_kernel = use_paged_kernel() and _paged_kernel_supported(
            d, block_size, m
        )
    if use_kernel:
        o, l, mx = _paged_decode_fused(
            qf, k_pool, v_pool, block_table, length, t, window=window,
            k_scale_pool=k_scale_pool, v_scale_pool=v_scale_pool,
        )
    else:
        o0 = jnp.zeros((b, hkv, group * t, d), f32)
        l0 = jnp.zeros((b, hkv, group * t), f32)
        m0 = jnp.full((b, hkv, group * t), _NEG_INF, f32)
        (o, l, mx), _ = jax.lax.scan(step, (o0, l0, m0), jnp.arange(m))
    # the tile attends to itself causally: key j' (position
    # length + j') is visible to row j iff j' <= j (the diagonal is
    # always inside any window >= 1) — merged as one t-key block
    s_cur = jnp.einsum(
        "bhqd,bhkd->bhqk", qf, k_cur.astype(f32)
    )  # [b, hkv, g*t, t]
    cur_w_scale = None
    if quantized:
        # same deferral for the tile's own keys/values: the tile is
        # quantized at the sow (it lands in the arenas as-is), so its
        # scores see exactly the rows every LATER step will read back
        s_cur = s_cur * k_cur_scale[..., 0][:, :, None, :]
        cur_w_scale = v_cur_scale[..., 0]  # [b, hkv, t]
    trif = _tile_causal_mask(group, t, window)
    s_cur = jnp.where(trif[None, None], s_cur, _NEG_INF)
    o, l, mx = softmax_merge(
        o, l, mx, s_cur, v_cur.astype(f32),  # already [b, hkv, t, d]
        w_scale=cur_w_scale,
    )
    out = softmax_finalize(o, l).reshape(b, hkv, group, t, d)
    out = out.reshape(b, h, t, d)
    return out[:, :, 0, :] if squeeze else out


# ------------------------------------------------- fused paged kernel


def _paged_kernel_supported(d, block_size, m):
    """Shape gate for the fused paged decode kernel. Interpret mode
    (CPU tests, FORCE_INTERPRET debugging) takes any shape — no tiling
    constraints apply. COMPILED Mosaic streams (1, block_size, 1, d)
    arena tiles, so the arena's lane dim d must be a 128 multiple and
    the block_size sublane dim 8-aligned: unlike q (a [b,h,t,d]-sized
    array, padded for free in _paged_decode_fused), padding the SHARED
    arenas would copy the whole pool every step — misaligned pools
    keep the scan. m == 0 (no table slots) has no pool to stream."""
    if m < 1:
        return False
    if interpret_mode():
        return True
    return d % 128 == 0 and block_size % 8 == 0


def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                  hkv, m, t, bs, window, quantized, n_rows):
    """Fused paged decode attention, one Mosaic program per
    (batch·kv_head, table slot) grid point.

    Scalar-prefetch operands (the vLLM PagedAttention shape): the
    flattened [b*m] block table and the [b] lengths land in SMEM before
    the grid runs, so the K/V BlockSpec index maps gather each slot's
    block HBM->VMEM by TABLE INDIRECTION — `tbl[batch*m + j]` IS the
    index map, -1 slots clamped to resident block 0 and masked here.

    Per step: the (1, bs, 1, d) k/v tiles collapse to (bs, d); int8
    rows dequantize IN-REGISTER by the (bs, 1) scale-leaf column
    broadcast (one multiply per row element in VMEM — algebraically
    the scan's score-tile/weight folding, chosen because the sublane
    broadcast needs no transpose of the scale column). Scores run in
    the exp2 domain like the flash kernels (log2e pre-folded into q's
    scale multiply), masked by the SAME _paged_valid predicate the
    scan uses, and accumulate into the fp32 VMEM scratch (o, l, m)
    online-softmax triple; the last slot writes the raw partials out
    (m converted back to natural log) for the shared current-tile
    merge + finalize in paged_decode_attention."""
    if quantized:
        ks_ref, vs_ref = rest[:2]
        rest = rest[2:]
    o_ref, l_ref, m_ref, acc_o, acc_l, acc_m = rest
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_o[:] = jnp.zeros_like(acc_o)
        acc_l[:] = jnp.zeros_like(acc_l)
        acc_m[:] = jnp.full_like(acc_m, _NEG_INF)

    batch = i // hkv
    bid = tbl_ref[batch * m + j]
    seq_len = len_ref[batch]

    kb = k_ref[0, :, 0, :].astype(jnp.float32)  # (bs, d)
    vb = v_ref[0, :, 0, :].astype(jnp.float32)
    if quantized:
        kb = kb * ks_ref[0, :, 0, :]  # (bs, 1) sublane broadcast
        vb = vb * vs_ref[0, :, 0, :]

    q = q_ref[0, 0]  # (n_rows, d), exp2-domain prescaled f32
    s = jax.lax.dot_general(
        q, kb, dimension_numbers=_dims(1, 1),
        preferred_element_type=jnp.float32,
    )  # (n_rows, bs), log2 units

    k_pos = j * bs + jax.lax.broadcasted_iota(
        jnp.int32, (n_rows, bs), 1
    )
    # row r of the padded tile is tile token r % t (group-major
    # [group, t] flatten; pad rows alias real positions and are
    # sliced off by the caller)
    row_pos = seq_len + (
        jax.lax.broadcasted_iota(jnp.int32, (n_rows, bs), 0) % t
    )
    s = jnp.where(
        _paged_valid(k_pos, bid, seq_len, row_pos, window), s, _NEG_INF
    )

    m_prev = acc_m[:]
    m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
    p = jnp.exp2(s - m_new)
    corr = jnp.exp2(m_prev - m_new)
    acc_l[:] = acc_l[:] * corr + p.sum(-1, keepdims=True)
    acc_o[:] = acc_o[:] * corr + jax.lax.dot_general(
        p, vb, dimension_numbers=_dims(1, 0),
        preferred_element_type=jnp.float32,
    )
    acc_m[:] = m_new

    @pl.when(j == m - 1)
    def _():
        o_ref[0, 0] = acc_o[:]
        l_ref[0, 0] = acc_l[:]
        # natural-log units at the boundary, like the flash epilogue:
        # nothing outside the kernel ever sees base-2 values
        m_ref[0, 0] = acc_m[:] * _LN2


def _paged_decode_fused(qf, k_pool, v_pool, block_table, length, t,
                        window=None, k_scale_pool=None,
                        v_scale_pool=None, rows=None):
    """pallas_call wrapper for _paged_kernel: returns the pool-stream
    online-softmax partials (o [b,hkv,g*t,d], l, m [b,hkv,g*t]) in
    fp32 natural-log units — drop-in for the lax.scan's carry, so
    paged_decode_attention's tile merge + finalize is shared verbatim.

    qf is the scan's query layout: [b, hkv, group*t, d], already scale-
    multiplied, f32. The row axis pads up to resolve_paged_rows() (the
    tuned sublane tile); k/v pools stream untouched — int8 arenas stay
    int8 through the DMA, scale leaves ride as (1, bs, 1, 1) tiles."""
    b, hkv, gt, d = qf.shape
    bs = k_pool.shape[1]
    m = block_table.shape[1]
    quantized = k_scale_pool is not None
    rows = resolve_paged_rows(rows)
    n_rows = max(rows, ((gt + rows - 1) // rows) * rows)
    q2 = qf.astype(jnp.float32) * _LOG2E  # exp2 domain
    if n_rows != gt:
        q2 = jnp.pad(
            q2, ((0, 0), (0, 0), (0, n_rows - gt), (0, 0))
        )
    tbl = jnp.asarray(block_table, jnp.int32).reshape(b * m)
    ln = jnp.asarray(length, jnp.int32)

    def _bh_spec(last):
        """Per-(batch, kv-head) tile, revisited across the j stream."""
        return pl.BlockSpec(
            (1, 1, n_rows, last),
            lambda i, j, tbl_ref, len_ref: (i // hkv, i % hkv, 0, 0),
            memory_space=pltpu.VMEM,
        )

    def _pool_spec(last):
        """THE tentpole index map: the scalar-prefetched block table
        routes the HBM->VMEM DMA — slot j of sequence i//hkv names the
        arena block to stream; -1 (unallocated) clamps to block 0,
        whose rows _paged_valid masks. Same-index revisits (clamped
        runs) elide the copy like the flash stream clamps."""
        return pl.BlockSpec(
            (1, bs, 1, last),
            lambda i, j, tbl_ref, len_ref: (
                jnp.maximum(tbl_ref[(i // hkv) * m + j], 0),
                0, i % hkv, 0,
            ),
            memory_space=pltpu.VMEM,
        )

    in_specs = [_bh_spec(d), _pool_spec(d), _pool_spec(d)]
    inputs = [q2, k_pool, v_pool]
    if quantized:
        in_specs += [_pool_spec(1), _pool_spec(1)]
        inputs += [k_scale_pool, v_scale_pool]
    kernel = functools.partial(
        _paged_kernel, hkv=hkv, m=m, t=t, bs=bs, window=window,
        quantized=quantized, n_rows=n_rows,
    )
    o, l, mx = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * hkv, m),
            in_specs=in_specs,
            out_specs=(_bh_spec(d), _bh_spec(1), _bh_spec(1)),
            scratch_shapes=[
                pltpu.VMEM((n_rows, d), jnp.float32),
                pltpu.VMEM((n_rows, 1), jnp.float32),
                pltpu.VMEM((n_rows, 1), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, n_rows, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, n_rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, n_rows, 1), jnp.float32),
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret_mode(),
    )(tbl, ln, *inputs)
    return o[:, :, :gt], l[:, :, :gt, 0], mx[:, :, :gt, 0]


def _check_window(window, lq, lk):
    """Sliding-window attention is defined for square self-attention
    only: with lq != lk a window can leave query rows with NO visible
    key, whose softmax is undefined (the jnp paths would emit mean(v),
    the kernel 0). Square shapes + window >= 1 guarantee the diagonal
    is always visible, so every row has at least one key."""
    if window is None:
        return
    if window < 1:
        raise ValueError("window must be >= 1, got %r" % (window,))
    if lq != lk:
        raise ValueError(
            "sliding-window attention requires square self-attention "
            "(lq == lk), got lq=%d lk=%d" % (lq, lk)
        )


def packed_positions(segments):
    """Per-token positions that RESTART at each segment boundary.

    segments: [..., l] int ids forming contiguous same-id runs (the
    sequence-packing layout). Returns int32 of the same shape: the
    token's offset within its own segment — what RoPE / learned
    position tables should see for packed rows."""
    segments = jnp.asarray(segments)
    l = segments.shape[-1]
    idx = jnp.broadcast_to(jnp.arange(l, dtype=jnp.int32),
                           segments.shape)
    is_start = jnp.concatenate(
        [
            jnp.ones_like(segments[..., :1], bool),
            segments[..., 1:] != segments[..., :-1],
        ],
        axis=-1,
    )
    starts = jax.lax.cummax(
        jnp.where(is_start, idx, 0), axis=segments.ndim - 1
    )
    return idx - starts


def apply_rope(x, positions, theta=10000.0):
    """Rotary position embedding (RoPE) over the head dimension.

    x: [b, h, l, d]; positions: [l] (shared across the batch) or
    [b, l] (per-row, the packed-sequence case) int/float absolute
    positions. Rotates feature pairs (i, i+d/2) by
    positions * theta^(-2i/d), so q·k after rotation depends only on
    RELATIVE distance — the property that lets ring/Ulysses sequence
    shards use their global positions with no learned table. Math in
    fp32, result in x.dtype. An odd tail feature (d % 2) passes
    through unrotated.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    positions = jnp.asarray(positions)
    angles = positions.astype(jnp.float32)[..., :, None] * freqs
    if positions.ndim == 1:
        cos = jnp.cos(angles)[None, None]  # [1, 1, l, half]
        sin = jnp.sin(angles)[None, None]
    else:  # [b, l] -> [b, 1, l, half]
        cos = jnp.cos(angles)[:, None]
        sin = jnp.sin(angles)[:, None]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:2 * half]
    rot = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    if d % 2:
        rot = jnp.concatenate([rot, xf[..., 2 * half:]], axis=-1)
    return rot.astype(x.dtype)


# --------------------------------------------------------- flash kernel


def _dims(contract_a, contract_b):
    return (((contract_a,), (contract_b,)), ((), ()))


def _block_run(qi, ki, block_q, block_k, causal, window, pos_offset=0):
    """Whether query block qi overlaps key block ki under the causal
    and/or sliding-window mask — the block-skip invariant shared by the
    forward and both backward kernels. Causal: some q position >= the
    block's first k position. Window: some k position inside the newest
    window of some q position (last k pos > first q pos - window).
    `pos_offset` (static) shifts the q positions — ring attention's
    off-diagonal rotations run the window band at offset r*shard_len."""
    run = True
    q0 = qi * block_q + pos_offset
    if causal:
        run = q0 + block_q - 1 >= ki * block_k
    if window is not None:
        # newest k in block inside some q's lookback window
        back = ki * block_k + block_k - 1 > q0 - window
        run = jnp.logical_and(run, back) if causal else back
        if not causal:
            # oldest k in block inside some q's lookahead window
            fwd = q0 + block_q - 1 > ki * block_k - window
            run = jnp.logical_and(run, fwd)
    return run


def _block_mask(s, qi, ki, block_q, block_k, causal, window,
                pos_offset=0):
    if not causal and window is None:
        return s
    if use_cond_mask():
        # Interior blocks — fully inside the causal/window region — need
        # no per-element mask: branch it out so only edge blocks pay the
        # iota/compare/select VPU work (~half the running blocks are
        # interior for plain causal). Opt-in (EDL_FLASH_COND_MASK=1)
        # until the hardware A/B proves the branch beats the
        # straight-line select under Mosaic's pipeliner.
        interior = _block_interior(qi, ki, block_q, block_k, causal,
                                   window, pos_offset)
        return jax.lax.cond(
            interior,
            lambda ss: ss,
            lambda ss: _block_mask_apply(
                ss, qi, ki, block_q, block_k, causal, window,
                pos_offset,
            ),
            s,
        )
    return _block_mask_apply(s, qi, ki, block_q, block_k, causal,
                             window, pos_offset)


def _block_interior(qi, ki, block_q, block_k, causal, window,
                    pos_offset):
    """Dynamic predicate: every (q, k) pair in the block is visible, so
    the per-element mask is the identity. Causal: the newest key is at
    or before the oldest query. Window: the extreme pair distances stay
    inside the band."""
    q0 = qi * block_q + pos_offset
    inside = True
    if causal:
        inside = ki * block_k + block_k - 1 <= q0
    if window is not None:
        back = (q0 + block_q - 1) - ki * block_k < window
        inside = jnp.logical_and(inside, back)
        if not causal:
            fwd = (ki * block_k + block_k - 1) - q0 < window
            inside = jnp.logical_and(inside, fwd)
    return inside


def _block_mask_apply(s, qi, ki, block_q, block_k, causal, window,
                      pos_offset):
    q_pos = qi * block_q + pos_offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    keep = True
    if causal:
        keep = q_pos >= k_pos
    if window is not None:
        in_w = q_pos - k_pos < window
        keep = jnp.logical_and(keep, in_w) if causal else in_w
        if not causal:
            keep = jnp.logical_and(keep, k_pos - q_pos < window)
    return jnp.where(keep, s, _NEG_INF)


def _mxu_cast(p, operand_dtype):
    """Cast an f32 probability/gradient matrix to the other matmul
    operand's dtype when that operand is bf16: an f32 LHS forces the
    MXU onto its (severalx slower) fp32 path, while bf16 x bf16 with an
    f32 preferred_element_type runs at full rate with f32 accumulation.
    p's values are softmax weights in [0, 1] (or ds of the same scale),
    so the bf16 rounding is well inside the bf16 output tolerance of
    the training paths that hit this; f32 inputs (tests, oracle
    comparisons) are left untouched."""
    if operand_dtype == jnp.bfloat16:
        return p.astype(jnp.bfloat16)
    return p


def _flash_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, window,
                  block_q, block_k, n_k, has_segs=False,
                  pos_offset=0):
    if has_segs:
        qseg_ref, kseg_ref = rest[:2]
        rest = rest[2:]
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip key blocks fully outside the causal/window mask
    run = _block_run(qi, ki, block_q, block_k, causal, window,
                     pos_offset)

    @pl.when(run)
    def _():
        # exp2 domain: log2e rides the existing scale multiply
        q = q_ref[0] * (scale * _LOG2E)
        s = jax.lax.dot_general(
            q, k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k), in log2 units
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window,
                        pos_offset)
        if has_segs:
            # sequence packing: mask cross-segment pairs.
            # qseg (block_q, 1) == kseg (1, block_k) broadcasts to s
            s = jnp.where(qseg_ref[0] == kseg_ref[0], s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            _mxu_cast(p, v_ref.dtype), v_ref[0],
            dimension_numbers=_dims(1, 0),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(ki == n_k - 1)
    def _():
        l = l_scr[:]
        o_ref[0] = (
            acc_scr[:] / jnp.maximum(l, 1e-30)
        ).astype(o_ref.dtype)
        # logsumexp residual for the backward kernels: exp(s - lse) == P.
        # m is in log2 units, so convert back to natural log here — no
        # consumer ever sees base-2 values. Defense in depth: a
        # fully-skipped row (l == 0; unreachable for the square shapes
        # _check_window enforces) gets a +inf-class sentinel so the
        # backward's exp(-1e30 - lse) underflows to 0 instead of
        # exploding.
        lse_ref[0] = jnp.where(
            l > 0.0,
            (m_scr[:] + jnp.log2(jnp.maximum(l, 1e-30))) * _LN2,
            -_NEG_INF,
        )


def _outer_spec(block, d):
    """Block indexed by grid dim 1 (the parallel/output dimension)."""
    return pl.BlockSpec(
        (1, block, d), lambda i, j, t: (i, j, 0),
        memory_space=pltpu.VMEM,
    )


# --- streamed-block DMA clamping -------------------------------------
# Mosaic's pipeline elides the HBM->VMEM copy when a block's index map
# returns the same indices as the previous grid step. The compute for
# blocks fully outside the causal/window mask is already skipped by
# pl.when(_block_run), but their input DMAs would still run — for
# causal attention that is ~half of all kv traffic fetched and thrown
# away. These clamps pin the streamed index to the nearest in-mask
# block, so out-of-mask steps revisit an already-resident block and the
# pipeline skips the copy. The bounds are the same inequalities as
# _block_run solved for the streamed index, so every step with
# run=True reads its true block; out-of-mask steps read a (resident,
# unused) one. Segments never relax the causal/window mask, so the
# clamps stay valid with packing.


def _kv_stream_clamp(causal, window, block_q, block_k, n_k, pos_offset):
    """Clamp for the forward/dq kernels' streamed k/v index t, given
    q-block index j."""
    if not causal and window is None:
        return None

    def clamp(j, t):
        q0 = j * block_q + pos_offset
        lo = 0
        hi = n_k - 1
        if causal:
            # run: q0 + block_q - 1 >= ki * block_k
            hi = jnp.minimum(hi, (q0 + block_q - 1) // block_k)
        if window is not None:
            # back: ki*block_k + block_k - 1 > q0 - window
            lo = jnp.maximum(
                lo, (q0 - window - block_k + 1) // block_k + 1
            )
            if not causal:
                # fwd: q0 + block_q - 1 > ki*block_k - window
                hi = jnp.minimum(
                    hi, (q0 + block_q + window - 2) // block_k
                )
        return jnp.maximum(jnp.minimum(t, hi), jnp.minimum(lo, n_k - 1))

    return clamp


def _q_stream_clamp(causal, window, block_q, block_k, n_q, pos_offset):
    """Clamp for the dk/dv kernel's streamed q-block index qb, given
    key-block index j — the _block_run inequalities solved for qb."""
    if not causal and window is None:
        return None

    def clamp(j, qb):
        lo = 0
        hi = n_q - 1
        if causal:
            # run: qb*block_q + pos_offset + block_q - 1 >= j*block_k
            lo = jnp.maximum(lo, (j * block_k - pos_offset) // block_q)
        if window is not None:
            # back: j*block_k + block_k - 1 > q0 - window
            hi = jnp.minimum(
                hi,
                (j * block_k + block_k + window - 2 - pos_offset)
                // block_q,
            )
            if not causal:
                # fwd: q0 + block_q - 1 > j*block_k - window
                lo = jnp.maximum(
                    lo,
                    (j * block_k - window - pos_offset - block_q + 1)
                    // block_q + 1,
                )
        return jnp.maximum(jnp.minimum(qb, hi), jnp.minimum(lo, n_q - 1))

    return clamp


def _inner_spec(block, d, clamp=None):
    """Block indexed by grid dim 2 (the sequential/streamed dimension)."""
    cl = clamp or (lambda j, t: t)
    return pl.BlockSpec(
        (1, block, d), lambda i, j, t: (i, cl(j, t), 0),
        memory_space=pltpu.VMEM,
    )


def _kv_inner_spec(block, d, h, hkv, clamp=None):
    """Streamed kv spec for the forward/dq kernels when k/v carry fewer
    heads than q (GQA): grid dim 0 indexes b*h q-rows; kv row = batch
    offset + q_head // group. Degenerates to _inner_spec at h == hkv."""
    if h == hkv:
        return _inner_spec(block, d, clamp)
    group = h // hkv
    cl = clamp or (lambda j, t: t)
    return pl.BlockSpec(
        (1, block, d),
        lambda i, j, t: ((i // h) * hkv + (i % h) // group, cl(j, t), 0),
        memory_space=pltpu.VMEM,
    )


def _dkv_q_spec(block, d, h, hkv, n_q, clamp=None):
    """Streamed q-side spec for the dk/dv kernel under GQA: grid dim 0
    indexes b*hkv kv-rows and grid dim 2 enumerates (group, q_block)
    pairs flattened as t = g * n_q + q_block, so each kv block
    accumulates over every q head in its group."""
    if h == hkv:
        # group == 1: row = i, t // n_q = 0, t % n_q = t
        return _inner_spec(block, d, clamp)
    group = h // hkv
    cl = clamp or (lambda j, qb: qb)
    return pl.BlockSpec(
        (1, block, d),
        lambda i, j, t: (
            (i // hkv) * h + (i % hkv) * group + t // n_q,
            cl(j, t % n_q), 0,
        ),
        memory_space=pltpu.VMEM,
    )



def _mosaic_params():
    """Grid semantics for all three flash kernels: (bh, output-block,
    streamed-block) = two parallel dims + one arbitrary (sequential
    accumulation over scratch). Lets Mosaic pipeline the parallel dims.
    `CompilerParams` comes from ops.dispatch — the one place the
    jax-0.4.37 `TPUCompilerParams` rename is resolved."""
    return CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _seg_specs(block_q, block_k, heads, dkv=False, n_q=1, clamp=None):
    """BlockSpec pair for the segment-id inputs: q-side ids ride as
    [b, lq, 1] column tiles, k-side as [b, 1, lk] row tiles so the
    in-kernel equality broadcasts to (block_q, block_k) without any
    reshape. `heads` is the grid-dim-0 head count (h, or hkv for the
    dk/dv kernel whose streamed dim enumerates (group, q_block)).
    `clamp` applies to the STREAMED side (k ids for the forward/dq
    kernels, q ids for dk/dv), matching the k/v (resp. q) tile the
    kernel actually reads at each step."""
    cl = clamp or (lambda j, t: t)
    if not dkv:
        return (
            pl.BlockSpec((1, block_q, 1),
                         lambda i, j, t: (i // heads, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k),
                         lambda i, j, t: (i // heads, 0, cl(j, t)),
                         memory_space=pltpu.VMEM),
        )
    return (
        pl.BlockSpec((1, block_q, 1),
                     lambda i, j, t: (i // heads, cl(j, t % n_q), 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k),
                     lambda i, j, t: (i // heads, 0, j),
                     memory_space=pltpu.VMEM),
    )


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   window=None, with_residuals=False, segments=None,
                   pos_offset=0):
    b, h, lq, d = q.shape
    hkv = k.shape[1]
    lk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(b * hkv, lk, d)
    v3 = v.reshape(b * hkv, lk, d)
    n_q = lq // block_q
    n_k = lk // block_k
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        window=window,
        block_q=block_q,
        block_k=block_k,
        n_k=n_k,
        has_segs=segments is not None,
        pos_offset=pos_offset,
    )
    kv_clamp = _kv_stream_clamp(causal, window, block_q, block_k, n_k,
                                pos_offset)
    in_specs = [
        _outer_spec(block_q, d),
        _kv_inner_spec(block_k, d, h, hkv, kv_clamp),
        _kv_inner_spec(block_k, d, h, hkv, kv_clamp),
    ]
    inputs = [q3, k3, v3]
    if segments is not None:
        q_seg, k_seg = segments
        in_specs += list(_seg_specs(block_q, block_k, h,
                                    clamp=kv_clamp))
        inputs += [
            q_seg.reshape(b, lq, 1),
            k_seg.reshape(b, 1, lk),
        ]
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=in_specs,
        out_specs=(
            _outer_spec(block_q, d),
            # lse rides as [bh, lq, 1] so stores stay (block_q, 1)
            # sublane columns — no 1-D reshape/transpose in the kernel
            _outer_spec(block_q, 1),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_mosaic_params(),
        interpret=interpret_mode() if interpret is None else interpret,
    )(*inputs)
    out = out.reshape(b, h, lq, d)
    if with_residuals:
        return out, lse.reshape(b, h, lq, 1)
    return out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, scale, causal, window,
                         block_q, block_k, n_k, has_segs=False,
                         pos_offset=0):
    if has_segs:
        qseg_ref, kseg_ref = rest[:2]
        rest = rest[2:]
    dq_ref, dq_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _block_run(qi, ki, block_q, block_k, causal, window,
                     pos_offset)

    @pl.when(run)
    def _():
        # exp2 domain (see _flash_kernel): fold log2e into the scale
        # and convert the saved natural-log lse on load
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        ) * (scale * _LOG2E)
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window,
                        pos_offset)
        if has_segs:
            s = jnp.where(qseg_ref[0] == kseg_ref[0], s, _NEG_INF)
        p = jnp.exp2(s - lse_ref[0] * _LOG2E)  # (block_q, block_k)
        if has_segs:
            # a row fully masked by segments (possible only in the
            # rectangular pair form) carries an lse of the -1e30 class,
            # so exp(s - lse) = exp(0) = 1 there; its true softmax
            # contribution is zero — force it so
            p = jnp.where(lse_ref[0] < 0.5 * _NEG_INF, 0.0, p)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            _mxu_cast(ds, k_ref.dtype), k_ref[0],
            dimension_numbers=_dims(1, 0),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, *rest, scale, causal, window,
                          block_q, block_k, n_q, n_q_total,
                          has_segs=False, pos_offset=0):
    if has_segs:
        qseg_ref, kseg_ref = rest[:2]
        rest = rest[2:]
    dk_ref, dv_ref, dk_scr, dv_scr = rest
    ki = pl.program_id(1)  # key block is the outer (parallel) dim here
    qi = pl.program_id(2)
    # under GQA the streamed dim enumerates (q_head_in_group, q_block)
    # pairs: the positional q block index for masking is qi % n_q
    # (identity when n_q_total == n_q, i.e. standard MHA)
    qb = qi % n_q

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _block_run(qb, ki, block_q, block_k, causal, window,
                     pos_offset)

    @pl.when(run)
    def _():
        # exp2 domain (see _flash_kernel)
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        ) * (scale * _LOG2E)
        s = _block_mask(s, qb, ki, block_q, block_k, causal, window,
                        pos_offset)
        if has_segs:
            s = jnp.where(qseg_ref[0] == kseg_ref[0], s, _NEG_INF)
        p = jnp.exp2(s - lse_ref[0] * _LOG2E)  # (block_q, block_k)
        if has_segs:
            # see _flash_bwd_dq_kernel: fully-segment-masked rows
            # (rectangular pair form) must contribute zero to dk/dv
            p = jnp.where(lse_ref[0] < 0.5 * _NEG_INF, 0.0, p)
        # dV_j += P^T dO ; dP = dO V^T ; dS = P*(dP - D) ; dK_j += dS^T Q
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            _mxu_cast(p, do_ref.dtype), do_ref[0],
            dimension_numbers=_dims(0, 0),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=_dims(1, 1),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            _mxu_cast(ds, q_ref.dtype), q_ref[0],
            dimension_numbers=_dims(0, 0),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q_total - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                    block_k, interpret, window=None, grad_dtype=None,
                    segments=None, pos_offset=0):
    """Two-pass flash backward: a dq kernel parallel over query blocks
    and a dk/dv kernel parallel over key blocks, both recomputing P from
    the saved logsumexp (the standard flash-attention backward; one
    matmul recompute instead of the O(L) blockwise-vjp scan).
    `grad_dtype` overrides the output dtype (ring attention asks for
    float32 partials so its cross-shard accumulation stays exact); the
    in-kernel accumulation is float32 either way.

    GQA (hkv < h): the dq pass reads kv blocks through the head-group
    index map; the dk/dv pass runs one kv-row per kv head and streams
    (group, q_block) pairs, so dk/dv come out group-summed in the native
    [b, hkv, lk, d] shape with no extra HBM round-trip."""
    b, h, lq, d = q.shape
    hkv = k.shape[1]
    group = h // hkv
    lk = k.shape[2]
    bh = b * h
    interp = interpret_mode() if interpret is None else interpret
    dq_dtype = grad_dtype or q.dtype
    dk_dtype = grad_dtype or k.dtype
    dv_dtype = grad_dtype or v.dtype
    n_q = lq // block_q
    n_k = lk // block_k
    # D_i = rowsum(dO * O), the softmax-jacobian diagonal term
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(b * hkv, lk, d)
    v3 = v.reshape(b * hkv, lk, d)
    do3 = g.reshape(bh, lq, d)
    lse3 = lse.reshape(bh, lq, 1)
    delta3 = delta.reshape(bh, lq, 1)

    seg_inputs = []
    if segments is not None:
        q_seg, k_seg = segments
        seg_inputs = [
            q_seg.reshape(b, lq, 1),
            k_seg.reshape(b, 1, lk),
        ]

    kv_clamp = _kv_stream_clamp(causal, window, block_q, block_k, n_k,
                                pos_offset)
    col_q = _outer_spec(block_q, 1)
    dq_in_specs = [
        _outer_spec(block_q, d),
        _kv_inner_spec(block_k, d, h, hkv, kv_clamp),
        _kv_inner_spec(block_k, d, h, hkv, kv_clamp),
        _outer_spec(block_q, d),
        col_q, col_q,
    ]
    if segments is not None:
        dq_in_specs += list(_seg_specs(block_q, block_k, h,
                                       clamp=kv_clamp))
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal,
            window=window, block_q=block_q, block_k=block_k, n_k=n_k,
            has_segs=segments is not None, pos_offset=pos_offset,
        ),
        grid=(bh, n_q, n_k),
        in_specs=dq_in_specs,
        out_specs=_outer_spec(block_q, d),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), dq_dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_mosaic_params(),
        interpret=interp,
    )(q3, k3, v3, do3, lse3, delta3, *seg_inputs)

    # key-block-parallel pass: q-side inputs stream over the inner dim
    # (all (group, q_block) pairs under GQA)
    q_clamp = _q_stream_clamp(causal, window, block_q, block_k, n_q,
                              pos_offset)
    q_spec = _dkv_q_spec(block_q, d, h, hkv, n_q, q_clamp)
    col_q_t = _dkv_q_spec(block_q, 1, h, hkv, n_q, q_clamp)
    dkv_in_specs = [
        q_spec, _outer_spec(block_k, d),
        _outer_spec(block_k, d), q_spec,
        col_q_t, col_q_t,
    ]
    if segments is not None:
        dkv_in_specs += list(
            _seg_specs(block_q, block_k, hkv, dkv=True, n_q=n_q,
                       clamp=q_clamp)
        )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal,
            window=window, block_q=block_q, block_k=block_k, n_q=n_q,
            n_q_total=group * n_q,
            has_segs=segments is not None, pos_offset=pos_offset,
        ),
        grid=(b * hkv, n_k, group * n_q),
        in_specs=dkv_in_specs,
        out_specs=(_outer_spec(block_k, d), _outer_spec(block_k, d)),
        out_shape=(
            jax.ShapeDtypeStruct((b * hkv, lk, d), dk_dtype),
            jax.ShapeDtypeStruct((b * hkv, lk, d), dv_dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_mosaic_params(),
        interpret=interp,
    )(q3, k3, v3, do3, lse3, delta3, *seg_inputs)
    return (
        dq.reshape(b, h, lq, d),
        dk.reshape(b, hkv, lk, d),
        dv.reshape(b, hkv, lk, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, segments, causal, scale, block_q, block_k,
           interpret, window):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret, window=window, segments=segments)


def _flash_fwd(q, k, v, segments, causal, scale, block_q, block_k,
               interpret, window):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, window=window,
                              with_residuals=True, segments=segments)
    return out, (q, k, v, segments, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, res,
               g):
    q, k, v, segments, out, lse = res
    dq, dk, dv = _flash_backward(q, k, v, out, lse, g, causal, scale,
                                 block_q, block_k, interpret,
                                 window=window, segments=segments)
    return dq, dk, dv, segments_float0(segments)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None, window=None,
                    segments=None):
    """Tiled online-softmax attention (Pallas). head_dim is zero-padded
    to the 128-lane width (zeros don't change q·k or add output columns
    that survive the final slice); falls back to blockwise_attention when
    Pallas is disabled or the sequence doesn't tile into the blocks.
    `window`: sliding-window/local attention (see naive_attention) — the
    block-skip predicate prunes out-of-window key blocks, so compute
    scales with window, not sequence. k/v may carry fewer heads than q
    (GQA/MQA): the kernels index kv blocks through the head-group map
    natively, no repeat is materialized. `segments` [b, l] int: sequence
    packing — attention confined to same-id runs in forward AND backward
    (the id tiles ride into the kernels as column/row blocks). With the
    single-array form every row sees at least itself; the rectangular
    (q_seg, k_seg) pair form (one ring rotation's geometry) CAN fully
    mask a row, and such rows return exactly 0 with zero gradient — the
    Pallas and blockwise backends are post-masked identically, so the
    two paths agree (ring itself merges unnormalized partials via
    attention_forward_lse instead)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    pair_form = isinstance(segments, (tuple, list))
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    group_size(q, k)  # validate GQA divisibility before kernel dispatch
    block_q = min(resolve_block(block_q, "q"), lq)
    block_k = min(resolve_block(block_k, "k"), lk)
    _check_window(window, lq, lk)
    segments = _check_segments(segments, q.shape[0], lq, lk)
    tiles = _flash_tiles(lq, lk, block_q, block_k)
    if not (use_pallas() and tiles):
        if use_pallas():
            logger.debug(
                "flash_attention falling back to blockwise: seq (%d, %d) "
                "does not tile into (%d, %d) blocks",
                lq, lk, block_q, block_k,
            )
        out = blockwise_attention(q, k, v, causal=causal, scale=scale,
                                  window=window, segments=segments)
    else:
        qp, kp, vp = _pad_lanes([q, k, v], d)
        out = _flash(qp, kp, vp, segments, causal, scale, block_q,
                     block_k, interpret, window)[..., :d]
    if pair_form:
        # the fully-masked-row contract: both backends leave a
        # degenerate value there (blockwise: mean(v); kernel: depends
        # on block skipping), so mask to exactly 0 — jnp.where also
        # zeroes the row's gradient, matching the backward kernels'
        # zero-contribution handling. O(lq*lk) elementwise, fused.
        q_seg, k_seg = segments
        masked = _fully_masked_rows(q_seg, k_seg, causal, window, lq, lk)
        out = jnp.where(masked[:, None, :, None], 0.0, out)
    return out


def _fully_masked_rows(q_seg, k_seg, causal, window, lq, lk,
                       chunk=2048):
    """[b, lq] bool: True where a query row has NO visible key under the
    segment/causal/window mask — semantics mirror _block_mask at
    pos_offset 0 (pair-form flash_attention is the only caller; ring
    rotations handle offsets through the lse sentinel instead).

    The visibility reduction runs over key CHUNKS (fori_loop), so peak
    memory is O(b * lq * chunk) rather than materializing the full
    [b, lq, lk] pair mask — shard lengths on the ring hot path can
    grow without this check growing with them. One chunk (lk <= 2048)
    is the single fused expression it always was."""
    q_pos = jnp.arange(lq)[:, None]

    def visible(k_lo, k_seg_c, width):
        k_pos = k_lo + jnp.arange(width)[None, :]
        keep = q_seg[:, :, None] == k_seg_c[:, None, :]
        if causal:
            keep = jnp.logical_and(keep, q_pos >= k_pos)
        if window is not None:
            in_w = q_pos - k_pos < window
            keep = jnp.logical_and(keep, in_w)
            if not causal:
                keep = jnp.logical_and(keep, k_pos - q_pos < window)
        return keep.any(-1)

    if lk <= chunk:
        return jnp.logical_not(visible(0, k_seg, lk))

    n_chunks = -(-lk // chunk)
    pad = n_chunks * chunk - lk
    # pad keys with a segment id no query can carry (ids are >= 0)
    k_seg_p = jnp.pad(k_seg, ((0, 0), (0, pad)), constant_values=-1)

    def body(c, acc):
        k_lo = c * chunk
        k_seg_c = jax.lax.dynamic_slice_in_dim(
            k_seg_p, k_lo, chunk, axis=1)
        return jnp.logical_or(acc, visible(k_lo, k_seg_c, chunk))

    any_visible = jax.lax.fori_loop(
        0, n_chunks, body,
        jnp.zeros(q_seg.shape, bool),
    )
    return jnp.logical_not(any_visible)


def jax_flash_attention(q, k, v, causal=False, scale=None, window=None):
    """Dispatch to jax's BUNDLED TPU flash kernel
    (jax.experimental.pallas.ops.tpu.flash_attention) — an alternative
    hot path the hardware sweep benchmarks against ours
    (scripts/bench_attention.py), exposed as the model-zoo
    attn_impl='jax_flash' so the flagship can adopt whichever kernel
    wins on the target chip without code edits. Same [b, h, l, d]
    layout; head_dim zero-padded to the 128-lane width like our kernel.
    Sliding windows are not supported by the bundled kernel; off-TPU
    falls back to the blockwise reference path."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if window is not None:
        raise ValueError(
            "attn_impl='jax_flash' does not support sliding-window "
            "attention; use the built-in flash kernel (attn_impl='auto')"
        )
    if not is_tpu_backend():
        return blockwise_attention(q, k, v, causal=causal, scale=scale)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _bundled,
    )

    # the bundled kernel wants equal head counts; expand GQA kv
    k = expand_kv(k, q.shape[1])
    v = expand_kv(v, q.shape[1])
    d = q.shape[-1]
    q, k, v = _pad_lanes([q, k, v], d)
    out = _bundled(q, k, v, causal=causal, sm_scale=scale)
    return out[..., :d]


# ------------------------------------------- ring-attention local compute
# Ring attention (parallel/context_parallel.py) needs attention in its
# "partial" form — (normalized output, logsumexp) per kv shard, merged
# online across ppermute rotations — and a backward that recomputes this
# shard's slice of the GLOBAL softmax from the merged logsumexp. These
# two functions are that surface: the Pallas kernels when they can run,
# the jnp paths otherwise. They are not differentiable themselves; the
# ring's custom_vjp composes them.


def _pad_lanes(arrays, d):
    if d % 128 == 0:
        return arrays
    widths = ((0, 0), (0, 0), (0, 0), (0, 128 - d % 128))
    return [jnp.pad(x, widths) for x in arrays]


def _flash_tiles(lq, lk, block_q, block_k):
    return (lq % block_q == 0 and lk % block_k == 0
            and block_q % 8 == 0 and block_k % 8 == 0)


def attention_forward_lse(q, k, v, causal=False, scale=None,
                          block_q=None, block_k=None, interpret=None,
                          segments=None, pos_offset=0, window=None):
    """Attention returning (out, logsumexp): out [b,h,lq,d] in q.dtype,
    lse float32 [b,h,lq]. Pallas flash kernel when available and the
    sequence tiles, else the blockwise scan. k/v may carry fewer heads
    than q (GQA). `segments`: packing mask, single array or
    (q_seg, k_seg) pair — the pair form serves ring rotations, where a
    row CAN be fully masked; such rows come back with lse = exactly
    _NEG_INF (their `o` is an unnormalized degenerate value, but an
    lse_merge weights it exp(_NEG_INF - finite) = 0, so merged results
    are exact)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    group_size(q, k)  # validate GQA divisibility
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    segments = _check_segments(segments, q.shape[0], lq, lk)
    bq = min(resolve_block(block_q, "q"), lq)
    bk = min(resolve_block(block_k, "k"), lk)
    if use_pallas() and _flash_tiles(lq, lk, bq, bk):
        qp, kp, vp = _pad_lanes([q, k, v], d)
        out, lse = _flash_forward(qp, kp, vp, causal, scale, bq, bk,
                                  interpret, with_residuals=True,
                                  segments=segments,
                                  pos_offset=pos_offset, window=window)
        out, lse = out[..., :d], lse[..., 0]
        if segments is not None or pos_offset:
            # a fully-segment-masked row leaves the kernel with
            # lse = -1e30 + log(lk) (p = exp(0) accumulates l = lk);
            # snap every +/-1e30-class value to exact _NEG_INF so the
            # lse_merge weight exp(lse_i - lse) is deterministically 0
            # and the flash/blockwise paths agree bit-for-bit
            lse = jnp.where(jnp.abs(lse) > -_NEG_INF * 0.5,
                            _NEG_INF, lse)
        return out, lse
    out, lse = blockwise_attention(q, k, v, causal=causal, scale=scale,
                                   with_lse=True, segments=segments,
                                   pos_offset=pos_offset,
                                   window=window)
    if segments is not None or pos_offset:
        # blockwise's empty-row lse is m+log(1e-30) ~ -1e30 already;
        # normalize exactly for deterministic merges
        lse = jnp.where(jnp.abs(lse) > -_NEG_INF * 0.5,
                        _NEG_INF, lse)
    return out, lse


def attention_backward_lse(q, k, v, out, lse, g, causal=False, scale=None,
                           block_q=None, block_k=None, interpret=None,
                           grad_dtype=None, segments=None,
                           pos_offset=0, window=None):
    """(dq, dk, dv) for attention given a saved logsumexp.

    `lse` may be the GLOBAL logsumexp of a ring while k/v are one shard:
    P = exp(q·k*scale - lse) is then this shard's exact slice of the
    global softmax, so per-shard partials sum to the exact gradient
    (`out`/`g` are the global output and its cotangent, entering through
    delta = rowsum(g*out)). Pallas two-pass kernels when available, else
    a dense jnp recompute (O(L^2) memory — the CPU/test fallback).
    `grad_dtype` (e.g. float32 for ring partial accumulation) overrides
    the default input-dtype outputs. Under GQA (k/v with fewer heads)
    dk/dv come back group-summed in the kv head count."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    hkv = k.shape[1]
    group = group_size(q, k)
    segments = _check_segments(segments, q.shape[0], lq, lk)
    bq = min(resolve_block(block_q, "q"), lq)
    bk = min(resolve_block(block_k, "k"), lk)
    if use_pallas() and _flash_tiles(lq, lk, bq, bk):
        qp, kp, vp, outp, gp = _pad_lanes([q, k, v, out, g], d)
        dq, dk, dv = _flash_backward(
            qp, kp, vp, outp, lse[..., None], gp, causal, scale, bq, bk,
            interpret, grad_dtype=grad_dtype, segments=segments,
            pos_offset=pos_offset, window=window,
        )
        return dq[..., :d], dk[..., :d], dv[..., :d]
    f32 = jnp.float32
    b = q.shape[0]
    k = expand_kv(k, q.shape[1])
    v = expand_kv(v, q.shape[1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(f32), k.astype(f32)) * scale
    q_pos_d = jnp.arange(lq)[:, None] + pos_offset
    k_pos_d = jnp.arange(lk)[None, :]
    if causal:
        s = jnp.where((q_pos_d >= k_pos_d)[None, None], s, _NEG_INF)
    if window is not None:
        in_w = (q_pos_d - k_pos_d) < window
        if not causal:
            in_w = in_w & ((k_pos_d - q_pos_d) < window)
        s = jnp.where(in_w[None, None], s, _NEG_INF)
    if segments is not None:
        q_seg, k_seg = segments
        s = jnp.where(
            (q_seg[:, :, None] == k_seg[:, None, :])[:, None],
            s, _NEG_INF,
        )
    p = jnp.exp(s - lse.astype(f32)[..., None])
    if segments is not None:
        # fully-segment-masked rows carry a -1e30-class lse; their true
        # softmax contribution is zero (see _flash_bwd_dq_kernel)
        p = jnp.where(lse.astype(f32)[..., None] < 0.5 * _NEG_INF,
                      0.0, p)
    gf = g.astype(f32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v.astype(f32))
    delta = jnp.sum(gf * out.astype(f32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(f32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(f32))
    if group > 1:  # GQA: sum the expanded-head grads back per kv head
        dk = dk.reshape(b, hkv, group, lk, d).sum(2)
        dv = dv.reshape(b, hkv, group, lk, d).sum(2)
    return (dq.astype(grad_dtype or q.dtype),
            dk.astype(grad_dtype or k.dtype),
            dv.astype(grad_dtype or v.dtype))
