"""Backend dispatch for Pallas kernels.

Compiled Pallas requires a TPU. Off-TPU the call sites take their
pure-jnp/XLA reference paths (interpreted Pallas is orders of magnitude
slower than XLA on CPU); kernel tests opt into interpreter mode with
ELASTICDL_TPU_FORCE_INTERPRET=1 so the exact same kernel code is what
they verify (tests/test_attention.py, tests/test_ops.py fixtures).

Env knobs:
  ELASTICDL_TPU_DISABLE_PALLAS=1  force the pure-jnp reference paths
  ELASTICDL_TPU_FORCE_INTERPRET=1 run the kernels in interpreter mode
                                  (opts non-TPU backends INTO the kernel
                                  path; on TPU, debugs the kernel without
                                  Mosaic)
"""

import os

import jax


def use_pallas():
    """Whether call sites should route through the Pallas kernels at all.

    On non-TPU backends the kernels could only run interpreted — orders
    of magnitude slower than the pure-jnp/XLA reference paths — so
    production CPU runs (the bench fallback, CPU-only users) take the
    reference paths and kernel tests opt in via FORCE_INTERPRET=1.
    """
    if os.environ.get("ELASTICDL_TPU_DISABLE_PALLAS", "") == "1":
        return False
    if os.environ.get("ELASTICDL_TPU_FORCE_INTERPRET", "") == "1":
        return True
    return is_tpu_backend()


def use_cond_mask():
    """Opt-in (EDL_FLASH_COND_MASK=1): branch the flash kernels'
    per-element causal/window mask out of interior (fully-visible)
    blocks via lax.cond — an hw_session A/B candidate; default stays
    the straight-line select until hardware proves the branch wins."""
    return os.environ.get("EDL_FLASH_COND_MASK", "") == "1"


def interpret_mode():
    """interpret= flag for pallas_call: compiled only on a real TPU.

    The TPU backend may register under a plugin platform name (e.g. a
    tunneled PJRT plugin) rather than "tpu", so identify hardware by the
    device's platform/kind, not the backend string alone.
    """
    if os.environ.get("ELASTICDL_TPU_FORCE_INTERPRET", "") == "1":
        return True
    return not is_tpu_backend()


def is_tpu_backend():
    """True when the default backend is real TPU hardware (including
    TPU plugins registered under a non-"tpu" platform name)."""
    backend = jax.default_backend()
    if backend == "tpu":
        return True
    if backend in ("cpu", "gpu", "cuda", "rocm"):
        return False
    # Unknown plugin platform: the only plugins this framework targets
    # are TPU tunnels, so treat it as TPU hardware.
    return True
