"""Backend dispatch for Pallas kernels.

Compiled Pallas requires a TPU. Off-TPU the call sites take their
pure-jnp/XLA reference paths (interpreted Pallas is orders of magnitude
slower than XLA on CPU); kernel tests opt into interpreter mode with
ELASTICDL_TPU_FORCE_INTERPRET=1 so the exact same kernel code is what
they verify (tests/test_attention.py, tests/test_ops.py fixtures).

Env knobs:
  ELASTICDL_TPU_DISABLE_PALLAS=1  force the pure-jnp reference paths
  ELASTICDL_TPU_FORCE_INTERPRET=1 run the kernels in interpreter mode
                                  (opts non-TPU backends INTO the kernel
                                  path; on TPU, debugs the kernel without
                                  Mosaic)
  EDL_DISABLE_PAGED_KERNEL=1      keep paged decode on the lax.scan
                                  oracle even where the fused Pallas
                                  kernel would engage (A/B + bisection
                                  knob; the scan is the parity fallback)

This module is also the ONE place the jax Pallas API version skew is
resolved: jax 0.4.37 ships the TPU compiler/memory-space types under
their old names (`pltpu.TPUCompilerParams`, `pltpu.TPUMemorySpace` with
no `HBM` member) while the current documented surface spells them
`pltpu.CompilerParams` / `pltpu.MemorySpace.HBM`. Every Pallas call
site imports `CompilerParams` / `MemorySpace` from HERE instead of
probing `pltpu` itself, so a jax upgrade (or downgrade) is a one-file
change and the kernels never crash with AttributeError on the other
side of the rename.
"""

import os

import jax
from jax.experimental.pallas import tpu as _pltpu

if hasattr(_pltpu, "CompilerParams"):
    CompilerParams = _pltpu.CompilerParams
else:  # jax 0.4.37: pre-rename spelling
    CompilerParams = _pltpu.TPUCompilerParams

if hasattr(_pltpu, "MemorySpace"):
    MemorySpace = _pltpu.MemorySpace
else:
    class MemorySpace(object):
        """jax-0.4.37 stand-in for `pltpu.MemorySpace`: same member
        names, values from `TPUMemorySpace`. 0.4.37 has no HBM member
        at all — ANY is the closest semantics (the compiler may leave
        the buffer off-chip and the kernel DMAs it explicitly), and it
        is exactly what the old API resolved HBM-style usage to."""

        ANY = _pltpu.TPUMemorySpace.ANY
        HBM = _pltpu.TPUMemorySpace.ANY
        VMEM = _pltpu.TPUMemorySpace.VMEM
        SMEM = _pltpu.TPUMemorySpace.SMEM
        SEMAPHORE = _pltpu.TPUMemorySpace.SEMAPHORE


def use_pallas():
    """Whether call sites should route through the Pallas kernels at all.

    On non-TPU backends the kernels could only run interpreted — orders
    of magnitude slower than the pure-jnp/XLA reference paths — so
    production CPU runs (the bench fallback, CPU-only users) take the
    reference paths and kernel tests opt in via FORCE_INTERPRET=1.
    """
    if os.environ.get("ELASTICDL_TPU_DISABLE_PALLAS", "") == "1":
        return False
    if os.environ.get("ELASTICDL_TPU_FORCE_INTERPRET", "") == "1":
        return True
    return is_tpu_backend()


def use_paged_kernel():
    """Whether paged_decode_attention should try the fused Pallas
    kernel (ops/attention.py::_paged_decode_fused) instead of the
    lax.scan oracle. Rides use_pallas() — same TPU/FORCE_INTERPRET/
    DISABLE_PALLAS ladder as every other kernel — with its own kill
    switch so the scan fallback stays one env var away during
    bring-up/bisection (the kernel is numerically tile-parallel where
    the scan is sequential; EDL_DISABLE_PAGED_KERNEL=1 pins the
    oracle). Shape support is the call site's problem
    (_paged_kernel_supported): this is only the policy bit."""
    if os.environ.get("EDL_DISABLE_PAGED_KERNEL", "") == "1":
        return False
    return use_pallas()


def use_cond_mask():
    """Opt-in (EDL_FLASH_COND_MASK=1): branch the flash kernels'
    per-element causal/window mask out of interior (fully-visible)
    blocks via lax.cond — an hw_session A/B candidate; default stays
    the straight-line select until hardware proves the branch wins."""
    return os.environ.get("EDL_FLASH_COND_MASK", "") == "1"


def interpret_mode():
    """interpret= flag for pallas_call: compiled only on a real TPU.

    The TPU backend may register under a plugin platform name (e.g. a
    tunneled PJRT plugin) rather than "tpu", so identify hardware by the
    device's platform/kind, not the backend string alone.
    """
    if os.environ.get("ELASTICDL_TPU_FORCE_INTERPRET", "") == "1":
        return True
    return not is_tpu_backend()


def is_tpu_backend():
    """True when the default backend is real TPU hardware (including
    TPU plugins registered under a non-"tpu" platform name)."""
    backend = jax.default_backend()
    if backend == "tpu":
        return True
    if backend in ("cpu", "gpu", "cuda", "rocm"):
        return False
    # Unknown plugin platform: the only plugins this framework targets
    # are TPU tunnels, so treat it as TPU hardware.
    return True
