"""Backend dispatch for Pallas kernels.

Compiled Pallas requires a TPU; everywhere else (CPU tests, the virtual
8-device mesh in tests/conftest.py) kernels run in Pallas interpreter mode
so the exact same kernel code is what the tests verify.

Env knobs:
  ELASTICDL_TPU_DISABLE_PALLAS=1  force the pure-jnp reference paths
  ELASTICDL_TPU_FORCE_INTERPRET=1 force interpreter mode even on TPU
"""

import os

import jax


def use_pallas():
    """Whether call sites should route through the Pallas kernels at all."""
    return os.environ.get("ELASTICDL_TPU_DISABLE_PALLAS", "") != "1"


def interpret_mode():
    """interpret= flag for pallas_call: compiled only on a real TPU."""
    if os.environ.get("ELASTICDL_TPU_FORCE_INTERPRET", "") == "1":
        return True
    return jax.default_backend() != "tpu"
