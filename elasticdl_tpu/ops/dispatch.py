"""Backend dispatch for Pallas kernels.

Compiled Pallas requires a TPU; everywhere else (CPU tests, the virtual
8-device mesh in tests/conftest.py) kernels run in Pallas interpreter mode
so the exact same kernel code is what the tests verify.

Env knobs:
  ELASTICDL_TPU_DISABLE_PALLAS=1  force the pure-jnp reference paths
  ELASTICDL_TPU_FORCE_INTERPRET=1 force interpreter mode even on TPU
"""

import os

import jax


def use_pallas():
    """Whether call sites should route through the Pallas kernels at all."""
    return os.environ.get("ELASTICDL_TPU_DISABLE_PALLAS", "") != "1"


def interpret_mode():
    """interpret= flag for pallas_call: compiled only on a real TPU.

    The TPU backend may register under a plugin platform name (e.g. a
    tunneled PJRT plugin) rather than "tpu", so identify hardware by the
    device's platform/kind, not the backend string alone.
    """
    if os.environ.get("ELASTICDL_TPU_FORCE_INTERPRET", "") == "1":
        return True
    return not is_tpu_backend()


def is_tpu_backend():
    """True when the default backend is real TPU hardware (including
    TPU plugins registered under a non-"tpu" platform name)."""
    backend = jax.default_backend()
    if backend == "tpu":
        return True
    if backend in ("cpu", "gpu", "cuda", "rocm"):
        return False
    # Unknown plugin platform: the only plugins this framework targets
    # are TPU tunnels, so treat it as TPU hardware.
    return True
