"""Sparse embedding-row kernels (Pallas TPU).

The TPU-native form of the reference's sparse PS path:

* `embedding_gather` — batched row lookup against an HBM-resident
  [vocab, dim] table via per-row DMA, replacing the
  pull_embedding_vectors RPC fan-out (worker/worker.py:380-409 →
  ps/embedding_table.py EmbeddingTable.get);
* `sparse_{sgd,momentum,adam,adagrad}_update` — in-place row updates
  against HBM tables (and their co-located slot tables), the Pallas
  counterpart of the Go sparse kernels that iterate rows and call the
  Eigen C API per row (go/pkg/kernel/kernel.go `SparseSGD`/`SparseAdam`/…
  → capi/kernel_api.cc). Only the rows named in `ids` move — the
  OptimizerWrapper contract (ps/optimizer_wrapper.py:70-351);
* `dedup_indexed_slices` — static-shape segment-sum dedup of duplicate
  ids, mirroring common/tensor_utils.py `deduplicate_indexed_slices`
  (the worker dedups before scattering grads to PS, worker.py:505-617).

Ids are int32; -1 is the padding id and marks rows to skip, which is how
dynamic id counts fit XLA's static shapes. Tables are aliased in/out
(`input_output_aliases`) so updates are true in-place HBM writes.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.ops import update_math as um
from elasticdl_tpu.ops.dispatch import (
    MemorySpace,
    interpret_mode,
    use_pallas,
)

PADDING_ID = -1

_ID_CHUNK = 8  # ids per grid program


def _pad_ids(ids, chunk=_ID_CHUNK):
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    n = ids.shape[0]
    padded = max(pl.cdiv(n, chunk), 1) * chunk
    return jnp.pad(ids, (0, padded - n), constant_values=PADDING_ID), n


def _pad_rows(rows, n_padded):
    rows = jnp.asarray(rows)
    return jnp.pad(rows, ((0, n_padded - rows.shape[0]), (0, 0)))


_LANE = 128


def _lane_pad(arr):
    """Pad the last dim up to a 128 multiple: Mosaic requires row-DMA
    slices to be lane-aligned, so tables with dim % 128 != 0 take a
    pad/unpad copy. The fast path (and the sane TPU table layout) is an
    embedding dim that is already a multiple of 128."""
    dim = arr.shape[-1]
    rem = dim % _LANE
    if rem == 0:
        return arr
    return jnp.pad(arr, ((0, 0), (0, _LANE - rem)))


# ------------------------------------------------------------------ gather


def _gather_kernel(ids_ref, table_ref, out_ref, sems):
    """One program gathers the whole id list: rows stream HBM→HBM with
    `_ID_CHUNK` DMAs in flight (double-buffered over the semaphore array),
    so row latency overlaps instead of serializing."""
    n = out_ref.shape[0]

    def get_dma(j):
        rid = jnp.maximum(ids_ref[j], 0)
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(rid, 1), :],
            out_ref.at[pl.ds(j, 1), :],
            sems.at[j % _ID_CHUNK],
        )

    def warm(j, _):
        get_dma(j).start()
        return 0

    jax.lax.fori_loop(0, min(_ID_CHUNK, n), warm, 0, unroll=True)

    def body(j, _):
        get_dma(j).wait()

        @pl.when(j + _ID_CHUNK < n)
        def _():
            get_dma(j + _ID_CHUNK).start()

        return 0

    jax.lax.fori_loop(0, n, body, 0)


def embedding_gather(table, ids, interpret=None):
    """table[ids] for int32 ids (any shape); padding ids gather row 0.

    The table never leaves HBM — touched rows are DMA'd straight into the
    (HBM) output, which is the point when vocab >> touched ids.
    """
    ids = jnp.asarray(ids, jnp.int32)
    vocab, dim = table.shape
    # ids outside [0, vocab) (incl. PADDING_ID) clamp into range — the
    # caller masks padding rows out (safe_embedding_lookup); an
    # out-of-range DMA would read/write arbitrary HBM.
    ids = jnp.clip(ids, 0, vocab - 1)
    out_shape = ids.shape + (dim,)
    if not use_pallas():
        return jnp.take(table, ids, axis=0)
    table = _lane_pad(table)
    flat_ids, n = _pad_ids(ids)
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=MemorySpace.HBM)],
            out_specs=pl.BlockSpec(memory_space=MemorySpace.HBM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((_ID_CHUNK,))],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (flat_ids.shape[0], table.shape[1]), table.dtype
        ),
        interpret=interpret_mode() if interpret is None else interpret,
    )(flat_ids, table)
    return out[:n, :dim].reshape(out_shape)


# ----------------------------------------------------------- row updates


def _row_update_call(kernel, ids, hyper, tables, grads, interpret):
    """Shared driver: `tables` are aliased in/out; `grads` is [n, dim]."""
    vocab, true_dim = tables[0].shape
    dtype = tables[0].dtype
    # out-of-range ids are skipped exactly like PADDING_ID: an OOB row
    # DMA-write would corrupt whatever lives past the table in HBM.
    ids = jnp.asarray(ids, jnp.int32)
    ids = jnp.where(ids >= vocab, PADDING_ID, ids)
    tables = [_lane_pad(t) for t in tables]
    dim = tables[0].shape[1]
    flat_ids, _ = _pad_ids(ids)
    grads = _lane_pad(_pad_rows(grads, flat_ids.shape[0]))
    grid = flat_ids.shape[0] // _ID_CHUNK
    hyper = jnp.stack([jnp.asarray(h, jnp.float32) for h in hyper])
    n_tables = len(tables)
    hbm = pl.BlockSpec(memory_space=MemorySpace.HBM)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # ids, hyper
            grid=(grid,),
            in_specs=[hbm] * n_tables
            + [
                pl.BlockSpec(
                    (_ID_CHUNK, dim),
                    lambda i, *_: (i, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=[hbm] * n_tables,
            scratch_shapes=[pltpu.VMEM((1, dim), dtype)] * n_tables
            + [pltpu.SemaphoreType.DMA((n_tables,))],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(t.shape, t.dtype) for t in tables
        ],
        input_output_aliases={2 + k: k for k in range(n_tables)},
        interpret=interpret_mode() if interpret is None else interpret,
    )(flat_ids, hyper, *tables, grads)
    if dim != true_dim:
        out = [o[:, :true_dim] for o in out]
    return tuple(out) if n_tables > 1 else out[0]


def _row_update_fallback(row_math, ids, tables, grads):
    """Pure-jnp path (ELASTICDL_TPU_DISABLE_PALLAS=1): gather touched
    rows, apply the shared update math, scatter back with OOB/padding ids
    dropped."""
    vocab = tables[0].shape[0]
    ids = jnp.asarray(ids, jnp.int32)
    safe = jnp.clip(ids, 0, vocab - 1)
    # negative ids would WRAP in .at[] indexing; push them out of range so
    # mode="drop" discards them together with ids >= vocab
    scatter_ids = jnp.where(ids < 0, vocab, ids)
    rows = [jnp.take(t, safe, axis=0) for t in tables]
    new_rows = row_math(rows, jnp.asarray(grads))
    outs = [
        t.at[scatter_ids].set(nr, mode="drop")
        for t, nr in zip(tables, new_rows)
    ]
    return tuple(outs) if len(outs) > 1 else outs[0]


def _row_copies(table_refs, rid, scratch, sems, inbound):
    copies = []
    for k, (r, s) in enumerate(zip(table_refs, scratch)):
        row = r.at[pl.ds(rid, 1), :]
        src, dst = (row, s) if inbound else (s, row)
        copies.append(pltpu.make_async_copy(src, dst, sems.at[k]))
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


def _make_row_kernel(n_tables, math_fn):
    """Build a kernel: per id, DMA `n_tables` rows in, apply `math_fn`
    (scratch rows + grad row + hyper → new scratch rows), DMA back."""

    def kernel(ids_ref, hyper_ref, *refs):
        tables_in = refs[:n_tables]
        grads_ref = refs[n_tables]
        tables_out = refs[n_tables + 1:n_tables + 1 + n_tables]
        scratch = refs[n_tables * 2 + 1:n_tables * 3 + 1]
        sems = refs[-1]
        base = pl.program_id(0) * _ID_CHUNK

        def body(j, _):
            rid = ids_ref[base + j]

            @pl.when(rid >= 0)
            def _():
                _row_copies(tables_in, rid, scratch, sems, inbound=True)
                math_fn(scratch, grads_ref[j, :], hyper_ref)
                _row_copies(tables_out, rid, scratch, sems, inbound=False)

            return 0

        jax.lax.fori_loop(0, _ID_CHUNK, body, 0)

    return kernel


def _sgd_math(scratch, g, h):
    scratch[0][0, :] = um.sgd_math(scratch[0][0, :], g, h[0])


_sgd_row_kernel = _make_row_kernel(1, _sgd_math)


def sparse_sgd_update(table, ids, grads, lr, interpret=None):
    """rows[ids] -= lr * grads (kernel.go `SparseSGD`). Ids must be
    deduplicated (see dedup_indexed_slices); -1 ids are skipped."""
    if not use_pallas():
        return _row_update_fallback(
            lambda rows, g: [um.sgd_math(rows[0], g, lr)],
            ids, [table], grads,
        )
    return _row_update_call(
        _sgd_row_kernel, ids, [lr], [table], grads, interpret
    )


def _momentum_math(scratch, g, h):
    scratch[0][0, :], scratch[1][0, :] = um.momentum_math(
        scratch[0][0, :], scratch[1][0, :], g, h[0], h[1], h[2]
    )


_momentum_row_kernel = _make_row_kernel(2, _momentum_math)


def sparse_momentum_update(table, velocity, ids, grads, lr, momentum=0.9,
                           nesterov=False, interpret=None):
    """Momentum on touched rows (kernel.go `SparseMomentum`).
    Returns (new_table, new_velocity)."""
    nesterov_f = 1.0 if nesterov else 0.0
    if not use_pallas():
        return _row_update_fallback(
            lambda rows, g: um.momentum_math(
                rows[0], rows[1], g, lr, momentum, nesterov_f
            ),
            ids, [table, velocity], grads,
        )
    return _row_update_call(
        _momentum_row_kernel,
        ids,
        [lr, momentum, 1.0 if nesterov else 0.0],
        [table, velocity],
        grads,
        interpret,
    )


def _adam_math(scratch, g, h):
    scratch[0][0, :], scratch[1][0, :], scratch[2][0, :] = um.adam_math(
        scratch[0][0, :], scratch[1][0, :], scratch[2][0, :], g,
        h[0], h[1], h[2], h[3],
    )


_adam_row_kernel = _make_row_kernel(3, _adam_math)


def sparse_adam_update(table, m, v, ids, grads, step, lr, beta1=0.9,
                       beta2=0.999, eps=1e-8, interpret=None):
    """Bias-corrected Adam on touched rows (kernel.go `SparseAdam`).
    Returns (new_table, new_m, new_v). `step` may be a traced array."""
    if not use_pallas():
        alpha = um.adam_alpha(lr, beta1, beta2, step)
        return _row_update_fallback(
            lambda rows, g: um.adam_math(
                rows[0], rows[1], rows[2], g, alpha, beta1, beta2, eps
            ),
            ids, [table, m, v], grads,
        )
    return _row_update_call(
        _adam_row_kernel,
        ids,
        [um.adam_alpha(lr, beta1, beta2, step), beta1, beta2, eps],
        [table, m, v],
        grads,
        interpret,
    )


def _adagrad_math(scratch, g, h):
    scratch[0][0, :], scratch[1][0, :] = um.adagrad_math(
        scratch[0][0, :], scratch[1][0, :], g, h[0], h[1]
    )


_adagrad_row_kernel = _make_row_kernel(2, _adagrad_math)


def sparse_adagrad_update(table, accum, ids, grads, lr, eps=1e-10,
                          interpret=None):
    """Adagrad on touched rows (kernel.go `SparseAdagrad`).
    Returns (new_table, new_accum)."""
    if not use_pallas():
        return _row_update_fallback(
            lambda rows, g: um.adagrad_math(rows[0], rows[1], g, lr, eps),
            ids, [table, accum], grads,
        )
    return _row_update_call(
        _adagrad_row_kernel, ids, [lr, eps], [table, accum], grads,
        interpret,
    )


# ------------------------------------------------------------------ dedup


def dedup_indexed_slices(ids, values, num_unique=None):
    """Sum `values` rows that share an id; static output size.

    Parity with common/tensor_utils.py `deduplicate_indexed_slices`
    (tf.math.segment_sum over sorted unique ids), under XLA's static
    shapes: the result always has `num_unique` (default len(ids)) rows,
    surplus rows padded with id PADDING_ID and zero values.

    Returns (unique_ids [k], summed [k, dim]).
    """
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    values = jnp.asarray(values)
    k = ids.shape[0] if num_unique is None else num_unique
    if not isinstance(ids, jax.core.Tracer):
        n_distinct = int(np.unique(np.asarray(ids)).size)
        if n_distinct > k:
            raise ValueError(
                "num_unique=%d < %d distinct ids: gradients would be "
                "silently dropped" % (k, n_distinct)
            )
    uniq, inverse = jnp.unique(
        ids, size=k, fill_value=PADDING_ID, return_inverse=True
    )
    inverse = inverse.reshape(-1)
    summed = jax.ops.segment_sum(values, inverse, num_segments=k)
    # unique() packs fill values at the end only when there are fewer than
    # `size` distinct ids; zero out rows whose slot is padding.
    summed = jnp.where((uniq != PADDING_ID)[:, None], summed, 0.0)
    return uniq, summed
