"""The optimizer update rules themselves, as pure array functions.

Single source of truth shared by the dense blocked kernels
(optimizer_kernels.py), the sparse row kernels (embedding_ops.py), and the
pure-jnp fallback paths — the same role kernel_api.cc plays for the
reference's dense and sparse Go wrappers (go/pkg/kernel/kernel.go calls
the one C function from both).

Each function maps (param(s), slot(s), grad, hyperparams) → new values;
inputs are arrays of any matching shape (a full tensor block or one row).
"""

import jax.numpy as jnp


def sgd_math(p, g, lr):
    return p - lr * g


def momentum_math(p, v, g, lr, mu, nesterov):
    """`nesterov` is a 0/1 float so the same code runs with traced
    hyperparams inside kernels."""
    v_new = mu * v + g
    step = jnp.where(nesterov > 0, mu * v_new + g, v_new)
    return p - lr * step, v_new


def adam_math(p, m, v, g, alpha, b1, b2, eps):
    """`alpha` is the bias-corrected step size
    lr * sqrt(1 - b2^t) / (1 - b1^t), precomputed by adam_alpha()."""
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    p_new = p - alpha * m_new / (jnp.sqrt(v_new) + eps)
    return p_new, m_new, v_new


def adam_amsgrad_math(p, m, v, ms, g, alpha, b1, b2, eps):
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    ms_new = jnp.maximum(ms, v_new)
    p_new = p - alpha * m_new / (jnp.sqrt(ms_new) + eps)
    return p_new, m_new, v_new, ms_new


def adam_alpha(lr, beta1, beta2, step):
    """Bias-corrected Adam step size; `step` is the 1-based update count
    and may be a traced array (Mosaic can't lower scalar powf, so this
    runs outside the kernel)."""
    t = jnp.asarray(step, jnp.float32)
    return lr * jnp.sqrt(1.0 - beta2**t) / (1.0 - beta1**t)


def adagrad_math(p, a, g, lr, eps):
    a_new = a + g * g
    p_new = p - lr * g / (jnp.sqrt(a_new) + eps)
    return p_new, a_new
