"""TPU-native compute kernels (Pallas).

The hot-op layer of the framework: where the reference backs its PS with
C++ Eigen kernels driven from Go (go/pkg/kernel/capi/kernel_api.cc:6-96,
go/pkg/kernel/kernel.go:14-199), this package provides the same optimizer
kernel family as Pallas TPU kernels — dense whole-tensor updates and
sparse row updates against an HBM-resident embedding table — plus the
row-gather that replaces the reference's pull_embedding_vectors RPC.

Every op has a pure-jnp reference path, used off-TPU; Pallas kernels run
compiled on TPU (tests opt into interpreter mode via
ELASTICDL_TPU_FORCE_INTERPRET=1 to exercise the kernel code anywhere).
"""

from elasticdl_tpu.ops.dispatch import use_pallas  # noqa: F401
from elasticdl_tpu.ops.losses import chunked_softmax_xent  # noqa: F401
from elasticdl_tpu.ops.embedding_ops import (  # noqa: F401
    dedup_indexed_slices,
    embedding_gather,
    sparse_adagrad_update,
    sparse_adam_update,
    sparse_momentum_update,
    sparse_sgd_update,
)
from elasticdl_tpu.ops.optimizer_kernels import (  # noqa: F401
    adagrad_update,
    adam_update,
    momentum_update,
    sgd_update,
)
