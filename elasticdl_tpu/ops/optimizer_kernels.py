"""Dense optimizer update kernels (Pallas TPU).

Kernel-family parity with the reference's C++ Eigen kernels
(go/pkg/kernel/capi/kernel_api.cc:6-96: SGD, Momentum(+nesterov),
Adam(+amsgrad, bias-corrected), Adagrad), rebuilt for the TPU VPU: tensors
are viewed as (rows, 128) lane-aligned matrices and updated block-by-block
in VMEM. On TPU these compile to single fused passes over HBM; the same
kernels run compiled on TPU; kernel tests opt into the Pallas
interpreter off-TPU (ELASTICDL_TPU_FORCE_INTERPRET=1).

The update rules live in update_math.py, shared with the sparse row
kernels and the pure-jnp fallback (ELASTICDL_TPU_DISABLE_PALLAS=1).

Measured on TPU v5e (scripts/bench_optimizer_kernels.py, 64M f32 params,
chained fetch-forced timing): Pallas and XLA-fused optax are identical
within noise — SGD 3.47 vs 3.46 ms, Adam 5.00 vs 5.04 ms (~230/375 GB/s;
HBM-bound either way). The Trainer therefore keeps stock optax, which XLA
additionally fuses into the compiled train step; these kernels remain the
standalone/native update path (parity with the reference's kernel API)
and the TPU smoke suite (tests/test_tpu_smoke.py) proves them compiled
on hardware.
"""

import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.ops import update_math as um
from elasticdl_tpu.ops.dispatch import interpret_mode, use_pallas

_LANE = 128
_BLOCK_ROWS = 256  # 256x128 f32 = 128 KiB per buffer per block


def _as_lanes(flat, padded):
    return jnp.pad(flat, (0, padded - flat.size)).reshape(-1, _LANE)


def _blocked_call(kernel, hyper, arrays, n_out, interpret=None):
    """Run `kernel(hyper_ref, *in_refs, *out_refs)` over lane-blocked views
    of same-shaped `arrays`; returns n_out arrays of the original shape."""
    shape = arrays[0].shape
    dtype = arrays[0].dtype
    n = int(math.prod(shape)) if shape else 1
    block = _LANE * _BLOCK_ROWS
    padded = max(pl.cdiv(n, block), 1) * block
    mats = [_as_lanes(jnp.asarray(a, dtype).reshape(-1), padded)
            for a in arrays]
    grid = padded // block
    hyper = jnp.stack([jnp.asarray(h, jnp.float32) for h in hyper])
    blockspec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                hyper.shape, lambda i: (0,), memory_space=pltpu.SMEM
            )
        ] + [blockspec] * len(mats),
        out_specs=[blockspec] * n_out,
        out_shape=[
            jax.ShapeDtypeStruct((padded // _LANE, _LANE), dtype)
        ] * n_out,
        interpret=interpret_mode() if interpret is None else interpret,
    )(hyper, *mats)
    return [o.reshape(-1)[:n].reshape(shape) for o in outs]


# --------------------------------------------------------------------- SGD


def _sgd_kernel(h_ref, p_ref, g_ref, out_ref):
    out_ref[:] = um.sgd_math(p_ref[:], g_ref[:], h_ref[0])


def sgd_update(param, grad, lr, interpret=None):
    """param - lr * grad (kernel_api.cc `SGD`)."""
    if not use_pallas():
        return um.sgd_math(jnp.asarray(param), jnp.asarray(grad), lr)
    (new_p,) = _blocked_call(
        _sgd_kernel, [lr], [param, grad], 1, interpret
    )
    return new_p


# ---------------------------------------------------------------- Momentum


def _momentum_kernel(h_ref, p_ref, v_ref, g_ref, p_out, v_out):
    p_out[:], v_out[:] = um.momentum_math(
        p_ref[:], v_ref[:], g_ref[:], h_ref[0], h_ref[1], h_ref[2]
    )


def momentum_update(param, velocity, grad, lr, momentum=0.9,
                    nesterov=False, interpret=None):
    """Heavy-ball / Nesterov momentum (kernel_api.cc `Momentum`).
    Returns (new_param, new_velocity)."""
    nesterov_f = 1.0 if nesterov else 0.0
    if not use_pallas():
        return um.momentum_math(
            jnp.asarray(param), jnp.asarray(velocity), jnp.asarray(grad),
            lr, momentum, nesterov_f,
        )
    new_p, new_v = _blocked_call(
        _momentum_kernel,
        [lr, momentum, nesterov_f],
        [param, velocity, grad],
        2,
        interpret,
    )
    return new_p, new_v


# -------------------------------------------------------------------- Adam


def _adam_kernel(h_ref, p_ref, m_ref, v_ref, g_ref, p_out, m_out, v_out):
    p_out[:], m_out[:], v_out[:] = um.adam_math(
        p_ref[:], m_ref[:], v_ref[:], g_ref[:],
        h_ref[0], h_ref[1], h_ref[2], h_ref[3],
    )


def _adam_amsgrad_kernel(h_ref, p_ref, m_ref, v_ref, ms_ref, g_ref,
                         p_out, m_out, v_out, ms_out):
    p_out[:], m_out[:], v_out[:], ms_out[:] = um.adam_amsgrad_math(
        p_ref[:], m_ref[:], v_ref[:], ms_ref[:], g_ref[:],
        h_ref[0], h_ref[1], h_ref[2], h_ref[3],
    )


def adam_update(param, m, v, grad, step, lr, beta1=0.9, beta2=0.999,
                eps=1e-8, max_square=None, interpret=None):
    """Bias-corrected Adam, optional amsgrad (kernel_api.cc `Adam`).

    `step` is the 1-based update count (bias correction uses beta^t) and
    may be a traced array.
    Returns (new_param, new_m, new_v) or (..., new_max_square) with amsgrad.
    """
    alpha = um.adam_alpha(lr, beta1, beta2, step)
    hyper = [alpha, beta1, beta2, eps]
    if not use_pallas():
        if max_square is None:
            return um.adam_math(
                jnp.asarray(param), jnp.asarray(m), jnp.asarray(v),
                jnp.asarray(grad), alpha, beta1, beta2, eps,
            )
        return um.adam_amsgrad_math(
            jnp.asarray(param), jnp.asarray(m), jnp.asarray(v),
            jnp.asarray(max_square), jnp.asarray(grad),
            alpha, beta1, beta2, eps,
        )
    if max_square is None:
        return tuple(_blocked_call(
            _adam_kernel, hyper, [param, m, v, grad], 3, interpret
        ))
    return tuple(_blocked_call(
        _adam_amsgrad_kernel, hyper, [param, m, v, max_square, grad], 4,
        interpret,
    ))


# ----------------------------------------------------------------- Adagrad


def _adagrad_kernel(h_ref, p_ref, a_ref, g_ref, p_out, a_out):
    p_out[:], a_out[:] = um.adagrad_math(
        p_ref[:], a_ref[:], g_ref[:], h_ref[0], h_ref[1]
    )


def adagrad_update(param, accum, grad, lr, eps=1e-10, interpret=None):
    """Adagrad (kernel_api.cc `Adagrad`). Returns (new_param, new_accum)."""
    if not use_pallas():
        return um.adagrad_math(
            jnp.asarray(param), jnp.asarray(accum), jnp.asarray(grad),
            lr, eps,
        )
    new_p, new_a = _blocked_call(
        _adagrad_kernel, [lr, eps], [param, accum, grad], 2, interpret
    )
    return new_p, new_a
