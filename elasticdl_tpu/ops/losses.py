"""Memory-efficient loss math for large-vocabulary LM heads.

At the bench flagship config ([32, 1024] tokens, 32k vocab) the naive
path materializes fp32 logits of [b, s, vocab] = 4.2 GB per step (plus
the bf16 matmul output and the softmax backward buffers) — several GB of
HBM traffic that dwarfs the head matmul's FLOP cost. `chunked_softmax_xent`
streams the head: the sequence is split into chunks, each chunk's logits
are computed, reduced to per-token cross entropy, and *rematerialized* in
the backward pass (`jax.checkpoint`), so peak logits residency drops from
O(b*s*vocab) to O(b*chunk*vocab) at the cost of one extra head matmul in
the backward (the classic remat trade: FLOPs for HBM).

The reference has no counterpart (its zoo tops out at ResNet50 with a
1k-way softmax — model_zoo/ has no sequence model); this op exists for
the net-new long-context families (model_zoo/transformer_lm & friends).
"""

import jax
import jax.numpy as jnp
import optax


def chunked_softmax_xent(hidden, kernel, labels, num_chunks=8):
    """Per-token cross entropy of an LM head without full logits.

    hidden:  [b, s, d]  final hidden states (any float dtype; the matmul
             runs in hidden.dtype, the softmax math in fp32)
    kernel:  [d, vocab] head projection (cast to hidden.dtype for the
             matmul, matching nn.Dense(dtype=...) promotion)
    labels:  [b, s]     int targets
    returns: [b, s]     fp32 cross entropy per token

    Matches
        optax.softmax_cross_entropy_with_integer_labels(
            (hidden @ kernel).astype(f32), labels)
    to fp32 accuracy. A sequence that does not divide into `num_chunks`
    is zero-padded up to the next multiple and the padded tail dropped
    from the result, so the peak-logits bound O(b * ceil(s/num_chunks)
    * vocab) holds for every length (awkward lengths cost padding
    compute, not memory).
    """
    b, s, d = hidden.shape
    num_chunks = min(num_chunks, s)
    if num_chunks <= 1:
        return _direct_xent(hidden, kernel, labels)
    c = -(-s // num_chunks)  # ceil
    if num_chunks * c != s:
        pad = num_chunks * c - s
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))

    # [n, b, c, ...] so lax.scan streams chunks down the sequence.
    h_chunks = hidden.reshape(b, num_chunks, c, d).swapaxes(0, 1)
    y_chunks = labels.reshape(b, num_chunks, c).swapaxes(0, 1)

    chunk_fn = jax.checkpoint(_direct_xent)

    def body(_, hy):
        h, y = hy
        return None, chunk_fn(h, kernel, y)

    _, ce = jax.lax.scan(body, None, (h_chunks, y_chunks))
    return ce.swapaxes(0, 1).reshape(b, num_chunks * c)[:, :s]


def _direct_xent(hidden, kernel, labels):
    logits = (hidden @ kernel.astype(hidden.dtype)).astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)
