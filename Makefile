# CI entry points (reference analogue: scripts/travis/run_job.sh wired
# into .travis.yml — here the same stages run locally or under any CI
# runner via `make ci`, and .github/workflows/ci.yml calls these exact
# targets).
#
# The suite is sharded by pytest markers (pytest.ini):
#   lint          — static analysis, runs BEFORE the shards: edl-lint
#                   (python -m elasticdl_tpu.analysis.lint — lock-
#                   discipline races, lock-order deadlock cycles,
#                   wrong-lock-held bindings, jit hazards, donated-
#                   buffer aliasing, blocking calls + deadline
#                   propagation in servicers/dispatch paths, must-
#                   release resource tracking, proto drift, the v3
#                   compile-discipline family on the value-origin
#                   dataflow — EDL105 recompile hazards, EDL106
#                   captured-constant bloat, EDL107 PRNG-key
#                   discipline — the born-gated EDL601 sharding
#                   discipline, and EDL000 unused-pragma policing;
#                   baseline in .edl-lint-baseline.json) + ruff
#                   (pinned in ci.yml; skipped with a notice when
#                   absent locally).
#                   Useful flags (pass via LINT_FLAGS): --jobs N fans
#                   per-file analysis over N processes (0 = one per
#                   CPU; output byte-identical to serial — worth it on
#                   multi-core runners), --format github emits GitHub
#                   Actions ::error annotations, --format sarif
#                   [--output F] writes byte-deterministic SARIF 2.1.0
#                   (CI uploads it to GitHub code scanning), and
#                   --fix-pragmas deletes unused suppressions.
#                   `make lint-changed` = --changed-only: lint only
#                   files changed vs the git merge base plus untracked
#                   ones — the pre-commit hook mode, sub-second on
#                   typical diffs (stale-baseline enforcement is
#                   skipped there; only full runs police baseline
#                   rot). Install the hook: bash
#                   scripts/install-hooks.sh.
#   default/fast  — everything NOT marked slow/integration (< 5 min,
#                   the per-commit gate)
#   drills        — the slow + integration shard: multi-process SPMD
#                   parity, elastic e2e (SIGKILL mid-job), gRPC
#                   master/worker, re-formation, elasticity bench
#   drill         — one real local training job + status validation,
#                   then the master SIGKILL/journal-recovery drill, the
#                   serving SIGTERM/SIGKILL drill, the multi-replica
#                   router chaos drill (SIGKILL + hot reload under live
#                   load, zero accepted-request loss, plus the router-
#                   kill phase: two journal-sharing router cells, the
#                   ring-owning cell SIGKILLed mid-load, its traffic
#                   rerouted by the CellFront and the corpse restarted
#                   from the journal), and the elastic-
#                   fleet autoscale drill (ramped Poisson load forces a
#                   scale-up, a SIGKILL forces a replacement, idle
#                   forces a drain-based scale-down; supervisor
#                   kill+restart re-adopts from its journal; p99 TTFT
#                   SLO held across every replica-count change), and
#                   the runtime-health stall drill (an injected
#                   scheduler wedge is self-reported, flight-recorder
#                   bundled, and replaced in seconds — beating the
#                   30 s lease heuristic — with zero accepted-request
#                   loss; a deliberate device-buffer leak is convicted
#                   by the memory accountant)
#   serve-smoke   — closed-loop load vs the generation server; emits
#                   the BENCH_SERVING.json serving-throughput record
#   bench-compare — gate a fresh serve-smoke record against the
#                   committed benchmarks/serving_baseline.json with
#                   per-metric tolerances (tok/s, goodput, bytes/
#                   token, the overhead-A/B ratio, zero steady
#                   recompiles); exit nonzero on regression
#   cluster-smoke — kind/minikube manifests smoke, env-gated
#                   (EDL_CLUSTER_FULL=1 + a reachable cluster)

PY ?= python
MESH_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
# keep in sync with the `lint` job in .github/workflows/ci.yml
RUFF_VERSION = 0.8.4
LINT_PATHS = elasticdl_tpu scripts tests

.PHONY: native lint lint-changed test-fast test-drills drill serve-smoke \
	bench-compare ci ci-fast cluster-smoke clean

native:
	$(MAKE) -C elasticdl_tpu/native

lint:
	env -u PYTHONPATH $(PY) -m elasticdl_tpu.analysis.lint $(LINT_FLAGS) $(LINT_PATHS)
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check $(LINT_PATHS); \
	elif $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check $(LINT_PATHS); \
	else \
		echo "ruff not installed (CI pins ruff==$(RUFF_VERSION)); skipping generic lint"; \
	fi

lint-changed:
	env -u PYTHONPATH $(PY) -m elasticdl_tpu.analysis.lint \
		--changed-only $(LINT_FLAGS) $(LINT_PATHS)

test-fast: native
	env -u PYTHONPATH $(MESH_ENV) $(PY) -m pytest tests/ -q \
		-m "not slow and not integration"

test-drills: native
	env -u PYTHONPATH $(MESH_ENV) $(PY) -m pytest tests/ -q \
		-m "slow or integration"

drill:
	bash scripts/run_local_job_drill.sh
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/run_master_kill_drill.py
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/run_server_kill_drill.py
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/run_router_chaos_drill.py
	env -u PYTHONPATH JAX_PLATFORMS=cpu EDL_KV_CACHE_DTYPE=int8 $(PY) scripts/run_autoscale_drill.py
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/run_stall_drill.py
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/run_rollout_drill.py

# Serving smoke: closed-loop load against the real continuous-batching
# server, one BENCH_*-style JSON line (p50/p99 TTFT, tok/s, goodput).
# The shared-prefix workload (a pool of common system prompts + random
# suffixes) runs FIVE ways at EQUAL KV bytes: dense, block-paged
# (private), paged + refcounted prefix sharing, paged + sharing +
# speculative decode (draft_k), and paged + sharing + spec over INT8
# arenas (quantized block storage, ~3x the blocks in the same bytes) —
# bytes-per-token, prefix-hit tokens, CoW copies, the draft accept
# rate and the int8 greedy-match rate vs the int8 dense oracle
# recorded under "kv"/"paged"/"paged_shared"/"paged_shared_spec"/
# "paged_int8"/"int8_vs_shared". Arrivals follow a
# --ramp piecewise-Poisson profile (the SAME generator the autoscale
# drill uses), so every record also carries per-phase percentiles
# under "phases". --kv_host_blocks additionally runs the tiered-KV
# eviction-pressure A/B (its own long-prefix int8 rig, device pool
# below the prefix working set, host tier off vs on at equal DEVICE
# KV bytes) and records the "host_vs_evict" ratio block: what share
# of the baseline's re-paid prefill tokens the host tier recovers by
# revival upload, with steady-state post-eviction TTFT. --profile
# records the per-step decode profiler breakdown (p50/p99 per phase:
# prefill/suffix_tile/decode/draft/verify_commit/scatter/
# revive_upload) under "profile" plus a validated /metrics scrape,
# and --overhead_ab runs the metrics+profiler plane OFF-vs-ON A/B on
# the paged+shared leg — the bench FAILS if the enabled plane costs
# more than 5% tokens/sec ("profiler_overhead" block).
serve-smoke:
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/bench_serving.py \
		--ramp "8:0.8,32:0.5,8:0.5" --compare_paged --kv_block_size 4 \
		--shared_prefix --prefix_len 16 --suffix_len 1:4 \
		--out_len 4:12 --draft_k 2 --kv_cache_dtype int8 \
		--kv_host_blocks 84 --profile --overhead_ab --disagg \
		--out BENCH_SERVING.json

# the bench-trajectory gate: run AFTER serve-smoke has written a
# fresh BENCH_SERVING.json; tolerances live in scripts/bench_compare.py
# (override per metric with --tol). Update the baseline deliberately,
# with the PR that improves it:
#   make serve-smoke && cp BENCH_SERVING.json benchmarks/serving_baseline.json
# The second leg re-runs the paged-attention microbench (scan + fused
# Pallas kernel, smoke-sized) and gates its ratio blocks against
# benchmarks/int8_scan_baseline.json the same way; refresh with
#   python scripts/bench_int8_scan.py --seq_len 128 --iters 20 \
#       --out benchmarks/int8_scan_baseline.json
bench-compare:
	env -u PYTHONPATH $(PY) scripts/bench_compare.py \
		--fresh BENCH_SERVING.json \
		--baseline benchmarks/serving_baseline.json
	env -u PYTHONPATH JAX_PLATFORMS=cpu $(PY) scripts/bench_int8_scan.py \
		--seq_len 128 --iters 20 --out BENCH_INT8_SCAN.json
	env -u PYTHONPATH $(PY) scripts/bench_compare.py \
		--fresh BENCH_INT8_SCAN.json \
		--baseline benchmarks/int8_scan_baseline.json

ci-fast: lint test-fast

ci: lint test-fast test-drills drill

cluster-smoke:
	bash scripts/run_cluster_job_smoke.sh

clean:
	$(MAKE) -C elasticdl_tpu/native clean
